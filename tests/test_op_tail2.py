"""Op-tail batch 2: ranking/pairwise losses, image ops, RNN unit cells,
candidate sampling, 3-D convs, host metrics.

Mirrors the reference unittest files (test_hinge_loss_op.py,
test_rank_loss_op.py, test_lrn_op.py, test_maxout_op.py, test_roi_pool_op.py,
test_gru_unit_op.py, test_nce.py, test_hsigmoid_op.py, test_chunk_eval_op.py,
test_mean_iou.py, test_bilinear_interp_op.py, ...): forward values against
a NumPy model + graph-level numeric gradients via the op harness.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_harness import check_grad, run_forward


rng = np.random.RandomState(7)


# ---------------------------------------------------------------------------
# losses: forward parity + numeric grads
# ---------------------------------------------------------------------------

def test_hinge_loss():
    x = rng.randn(6, 1).astype("float64")
    y = rng.randint(0, 2, (6, 1)).astype("float64")
    (out,) = run_forward(
        lambda v: fluid.layers.hinge_loss(v["x"], v["y"]), {"x": x, "y": y})
    np.testing.assert_allclose(
        out, np.maximum(0, 1 - (2 * y - 1) * x), rtol=1e-6)
    check_grad(lambda v: fluid.layers.hinge_loss(v["x"], v["y"]),
               {"x": x + 0.3, "y": y}, wrt=["x"])


def test_log_loss():
    p = rng.uniform(0.1, 0.9, (8, 1)).astype("float64")
    y = rng.randint(0, 2, (8, 1)).astype("float64")
    (out,) = run_forward(
        lambda v: fluid.layers.log_loss(v["p"], v["y"]), {"p": p, "y": y})
    ref = -y * np.log(p + 1e-4) - (1 - y) * np.log(1 - p + 1e-4)
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    check_grad(lambda v: fluid.layers.log_loss(v["p"], v["y"]),
               {"p": p, "y": y}, wrt=["p"])


def test_rank_loss():
    left = rng.randn(5, 1).astype("float64")
    right = rng.randn(5, 1).astype("float64")
    label = rng.randint(0, 2, (5, 1)).astype("float64")
    (out,) = run_forward(
        lambda v: fluid.layers.rank_loss(v["l"], v["a"], v["b"]),
        {"l": label, "a": left, "b": right})
    o = left - right
    np.testing.assert_allclose(out, np.log1p(np.exp(o)) - label * o,
                               rtol=1e-6)
    check_grad(lambda v: fluid.layers.rank_loss(v["l"], v["a"], v["b"]),
               {"l": label, "a": left, "b": right}, wrt=["a", "b"])


def test_margin_rank_loss_and_modified_huber():
    x1 = rng.randn(6, 1).astype("float64")
    x2 = rng.randn(6, 1).astype("float64")
    lab = np.where(rng.rand(6, 1) > 0.5, 1.0, -1.0)
    (out,) = run_forward(
        lambda v: fluid.layers.margin_rank_loss(v["l"], v["a"], v["b"],
                                                margin=0.1),
        {"l": lab, "a": x1, "b": x2})
    np.testing.assert_allclose(
        out, np.maximum(0, -lab * (x1 - x2) + 0.1), rtol=1e-6)

    x = rng.randn(8, 1).astype("float64")
    y = rng.randint(0, 2, (8, 1)).astype("float64")
    (mh,) = run_forward(
        lambda v: fluid.layers.modified_huber_loss(v["x"], v["y"]),
        {"x": x, "y": y})
    z = x * (2 * y - 1)
    ref = np.where(z < -1, -4 * z, np.where(z < 1, (1 - z) ** 2, 0.0))
    np.testing.assert_allclose(mh, ref, rtol=1e-6)
    check_grad(lambda v: fluid.layers.modified_huber_loss(v["x"], v["y"]),
               {"x": x, "y": y}, wrt=["x"])


def test_l2_losses_and_cos_sim():
    x = rng.randn(4, 5).astype("float64")
    y = rng.randn(4, 5).astype("float64")
    (d,) = run_forward(
        lambda v: fluid.layers.squared_l2_distance(v["x"], v["y"]),
        {"x": x, "y": y})
    np.testing.assert_allclose(
        d, ((x - y) ** 2).sum(1, keepdims=True), rtol=1e-6)
    (n,) = run_forward(lambda v: fluid.layers.squared_l2_norm(v["x"]),
                       {"x": x})
    np.testing.assert_allclose(n, [(x ** 2).sum()], rtol=1e-6)
    (l1,) = run_forward(lambda v: fluid.layers.l1_norm(v["x"]), {"x": x})
    np.testing.assert_allclose(l1, [np.abs(x).sum()], rtol=1e-6)
    (cs,) = run_forward(lambda v: fluid.layers.cos_sim(v["x"], v["y"]),
                        {"x": x, "y": y})
    ref = (x * y).sum(1) / (np.linalg.norm(x, axis=1)
                            * np.linalg.norm(y, axis=1))
    np.testing.assert_allclose(cs.reshape(-1), ref, rtol=1e-5)
    check_grad(lambda v: fluid.layers.cos_sim(v["x"], v["y"]),
               {"x": x, "y": y}, wrt=["x", "y"], rtol=5e-3)


def test_bilinear_tensor_product_grad():
    x = rng.randn(3, 4).astype("float64")
    y = rng.randn(3, 5).astype("float64")
    check_grad(
        lambda v: fluid.layers.bilinear_tensor_product(v["x"], v["y"], 6),
        {"x": x, "y": y}, wrt=["x", "y"], rtol=5e-3)


def test_label_smooth_and_smooth_l1():
    x = np.eye(4, 6).astype("float64")
    (out,) = run_forward(
        lambda v: fluid.layers.label_smooth(v["x"], epsilon=0.1), {"x": x})
    np.testing.assert_allclose(out, 0.9 * x + 0.1 / 6, rtol=1e-6)


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------

def test_shape_ops():
    x = rng.randn(2, 3, 4).astype("float32")
    (f,) = run_forward(lambda v: fluid.layers.flatten(v["x"], axis=2),
                       {"x": x})
    assert f.shape == (6, 4)
    (r,) = run_forward(lambda v: fluid.layers.reverse(v["x"], axis=1),
                       {"x": x})
    np.testing.assert_allclose(r, x[:, ::-1])
    outs = run_forward(lambda v: fluid.layers.unstack(v["x"], axis=0),
                       {"x": x})
    assert len(outs) == 2 and np.allclose(outs[1], x[1])
    (c,) = run_forward(
        lambda v: fluid.layers.crop(v["x"], shape=[2, 2, 2],
                                    offsets=[0, 1, 1]), {"x": x})
    np.testing.assert_allclose(c, x[:, 1:3, 1:3])
    (p,) = run_forward(
        lambda v: fluid.layers.pad2d(v["x4"], [1, 1, 2, 2], mode="reflect"),
        {"x4": rng.randn(1, 2, 4, 4).astype("float32")})
    assert p.shape == (1, 2, 6, 8)
    (s,) = run_forward(lambda v: fluid.layers.shape(v["x"]), {"x": x})
    np.testing.assert_array_equal(s, [2, 3, 4])


def test_pad_constant_like_and_multiplex_and_argsort():
    x = np.zeros((4, 5), "float32")
    y = rng.randn(2, 3).astype("float32")
    (p,) = run_forward(
        lambda v: fluid.layers.pad_constant_like(v["x"], v["y"], 9.0),
        {"x": x, "y": y})
    assert p.shape == (4, 5) and p[3, 4] == 9.0 and np.allclose(p[:2, :3], y)

    a = rng.randn(4, 3).astype("float32")
    b = rng.randn(4, 3).astype("float32")
    ids = np.array([[0], [1], [0], [1]], "int32")
    (m,) = run_forward(
        lambda v: fluid.layers.multiplex([v["a"], v["b"]], v["i"]),
        {"a": a, "b": b, "i": ids})
    np.testing.assert_allclose(m, np.stack([a[0], b[1], a[2], b[3]]))

    (so, si) = run_forward(lambda v: fluid.layers.argsort(v["a"], axis=1),
                           {"a": a})
    np.testing.assert_allclose(so, np.sort(a, axis=1))
    np.testing.assert_array_equal(si, np.argsort(a, axis=1))


def test_sequence_mask_and_scatter():
    lens = np.array([3, 1, 4], "int64")
    (m,) = run_forward(
        lambda v: fluid.layers.sequence_mask(v["l"], maxlen=5, dtype="int32"),
        {"l": lens})
    assert m.shape == (3, 5)
    np.testing.assert_array_equal(m[0], [1, 1, 1, 0, 0])

    x = np.zeros((2, 6), "float64")
    ids = np.array([[0, 2], [1, 3]], "int64")
    upd = rng.randn(2, 2).astype("float64")
    (out,) = run_forward(
        lambda v: fluid.layers.sequence_scatter(v["x"], v["i"], v["u"]),
        {"x": x, "i": ids, "u": upd})
    assert out[0, 0] == upd[0, 0] and out[1, 3] == upd[1, 1]


# ---------------------------------------------------------------------------
# image ops
# ---------------------------------------------------------------------------

def test_prelu_lrn_maxout_affine_channel():
    x = rng.randn(2, 4, 5, 5).astype("float64")
    alpha = np.array([0.25], "float64")
    (out,) = run_forward(
        lambda v: fluid.layers.prelu(v["x"], "all"), {"x": x})
    np.testing.assert_allclose(out, np.maximum(x, 0) + 0.25 * np.minimum(x, 0))
    check_grad(lambda v: fluid.layers.prelu(v["x"], "channel"),
               {"x": x}, wrt=["x"])

    (lrn_out,) = run_forward(
        lambda v: fluid.layers.lrn(v["x"], n=3, k=1.0, alpha=1e-2, beta=0.5),
        {"x": x})
    sq = x * x
    pad = np.pad(sq, ((0, 0), (1, 1), (0, 0), (0, 0)))
    mid = 1.0 + 1e-2 * (pad[:, :4] + pad[:, 1:5] + pad[:, 2:6])
    np.testing.assert_allclose(lrn_out, x * mid ** -0.5, rtol=1e-5)

    (mo,) = run_forward(lambda v: fluid.layers.maxout(v["x"], groups=2),
                        {"x": x})
    np.testing.assert_allclose(mo, x.reshape(2, 2, 2, 5, 5).max(axis=2))

    s = rng.randn(4).astype("float64")
    b = rng.randn(4).astype("float64")
    (ac,) = run_forward(
        lambda v: fluid.layers.affine_channel(v["x"], v["s"], v["b"]),
        {"x": x, "s": s, "b": b})
    np.testing.assert_allclose(
        ac, x * s.reshape(1, 4, 1, 1) + b.reshape(1, 4, 1, 1), rtol=1e-6)


def test_bilinear_interp_matches_numpy():
    x = rng.randn(2, 3, 4, 4).astype("float64")
    oh = ow = 7
    (out,) = run_forward(
        lambda v: fluid.layers.resize_bilinear(v["x"], out_shape=[oh, ow]),
        {"x": x})
    rh, rw = 3 / 6, 3 / 6
    ref = np.zeros((2, 3, oh, ow))
    for i in range(oh):
        for j in range(ow):
            yy, xx = i * rh, j * rw
            y0, x0 = int(yy), int(xx)
            y1, x1 = min(y0 + 1, 3), min(x0 + 1, 3)
            wy, wx = yy - y0, xx - x0
            ref[:, :, i, j] = ((1 - wy) * (1 - wx) * x[:, :, y0, x0]
                               + (1 - wy) * wx * x[:, :, y0, x1]
                               + wy * (1 - wx) * x[:, :, y1, x0]
                               + wy * wx * x[:, :, y1, x1])
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    check_grad(
        lambda v: fluid.layers.resize_bilinear(v["x"], out_shape=[oh, ow]),
        {"x": x}, wrt=["x"])


def test_roi_pool_reference_bins():
    # ROI spanning rows 0..2 pooled to 2 bins: reference overlapping
    # boundaries put row 1 in BOTH bins
    x = np.arange(16, dtype="float64").reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 2, 2]], "float64")  # batch 0, x1 y1 x2 y2
    (out,) = run_forward(
        lambda v: fluid.layers.roi_pool(v["x"], v["r"], 2, 2, 1.0),
        {"x": x, "r": rois})
    # bins: rows [0,2)/[1,3), cols same → maxes 5, 6, 9, 10
    np.testing.assert_allclose(out.reshape(2, 2), [[5, 6], [9, 10]])


def test_max_pool_with_index_grad_routing():
    x = rng.randn(2, 3, 6, 6).astype("float64")

    def build(v):
        helper = fluid.layer_helper.LayerHelper("max_pool2d_with_index")
        out = helper.create_variable_for_type_inference(
            v["x"].dtype, shape=(2, 3, 3, 3))
        mask = helper.create_variable_for_type_inference(
            "int64", shape=(2, 3, 3, 3), stop_gradient=True)
        helper.append_op("max_pool2d_with_index", {"X": [v["x"]]},
                         {"Out": [out], "Mask": [mask]},
                         {"ksize": [2, 2], "strides": [2, 2]})
        return out

    check_grad(build, {"x": x}, wrt=["x"])


def test_im2sequence_shapes():
    x = rng.randn(2, 3, 6, 6).astype("float32")
    (out,) = run_forward(
        lambda v: fluid.layers.im2sequence(v["x"], filter_size=2, stride=2),
        {"x": x})
    assert out.shape == (2, 9, 12)
    # first patch of first image = x[0,:,0:2,0:2] flattened channel-major
    np.testing.assert_allclose(out[0, 0], x[0, :, 0:2, 0:2].reshape(-1),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# RNN unit cells
# ---------------------------------------------------------------------------

def test_gru_unit_matches_numpy():
    B, D = 3, 4
    x = rng.randn(B, 3 * D).astype("float64")
    hp = rng.randn(B, D).astype("float64")

    (h, rhp, gate) = run_forward(
        lambda v: fluid.layers.gru_unit(v["x"], v["h"], 3 * D,
                                        bias_attr=False),
        {"x": x, "h": hp})
    # pull the initialized weight back out is awkward; check shapes + the
    # identity h = u*(c-h_prev)+h_prev holds for the returned gate parts
    u, r, c = gate[:, :D], gate[:, D:2 * D], gate[:, 2 * D:]
    np.testing.assert_allclose(h, u * (c - hp) + hp, rtol=1e-5)
    np.testing.assert_allclose(rhp, r * hp, rtol=1e-5)


def test_lstm_unit_and_grad():
    B, D = 3, 4
    x = rng.randn(B, 5).astype("float64")
    h = rng.randn(B, D).astype("float64")
    c = rng.randn(B, D).astype("float64")

    def build(v):
        hh, cc = fluid.layers.lstm_unit(v["x"], v["h"], v["c"],
                                        forget_bias=1.0)
        return hh

    check_grad(build, {"x": x, "h": h, "c": c}, wrt=["x", "c"], rtol=5e-3)


def test_dynamic_lstmp_shapes():
    B, T, H, P = 2, 5, 6, 3
    x = rng.randn(B, T, 4 * H).astype("float32")

    def build(v):
        proj, cell = fluid.layers.dynamic_lstmp(v["x"], 4 * H, P)
        return fluid.layers.reduce_sum(proj)

    (s,) = run_forward(build, {"x": x})
    assert np.isfinite(s)


def test_conv_shift():
    B, M, N = 2, 7, 3
    x = rng.randn(B, M).astype("float64")
    y = rng.randn(B, N).astype("float64")

    def build(v):
        helper = fluid.layer_helper.LayerHelper("conv_shift")
        out = helper.create_variable_for_type_inference(v["x"].dtype,
                                                        shape=(B, M))
        helper.append_op("conv_shift", {"X": [v["x"]], "Y": [v["y"]]},
                         {"Out": [out]}, {})
        return out

    (out,) = run_forward(build, {"x": x, "y": y})
    ref = np.zeros((B, M))
    half = (N - 1) // 2
    for i in range(M):
        for j in range(-half, N - half):
            ref[:, i] += x[:, (i + j) % M] * y[:, j + half]
    np.testing.assert_allclose(out, ref, rtol=1e-6)


# ---------------------------------------------------------------------------
# 3-D conv family
# ---------------------------------------------------------------------------

def test_conv3d_pool3d_grads():
    x = rng.randn(1, 2, 4, 4, 4).astype("float64")
    check_grad(
        lambda v: fluid.layers.conv3d(v["x"], 3, 2, bias_attr=False),
        {"x": x}, wrt=["x"], rtol=5e-3)
    (p,) = run_forward(
        lambda v: fluid.layers.pool3d(v["x"], 2, "avg", 2), {"x": x})
    np.testing.assert_allclose(
        p, x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7)),
        rtol=1e-6)


def test_conv3d_transpose_shape_roundtrip():
    x = rng.randn(1, 3, 3, 3, 3).astype("float32")
    (out,) = run_forward(
        lambda v: fluid.layers.conv3d_transpose(v["x"], 2, 2, stride=2,
                                                bias_attr=False), {"x": x})
    assert out.shape == (1, 2, 6, 6, 6)


# ---------------------------------------------------------------------------
# candidate sampling / random
# ---------------------------------------------------------------------------

def test_nce_trains_down():
    B, D, V = 8, 6, 40
    x = rng.randn(B, D).astype("float32")
    lab = rng.randint(0, V, (B, 1)).astype("int64")

    def build(v):
        cost = fluid.layers.nce(v["x"], v["l"], V, num_neg_samples=5)
        return fluid.layers.mean(cost)

    (c0,) = run_forward(build, {"x": x, "l": lab})
    assert np.isfinite(c0) and c0 > 0


def test_hsigmoid_loss_and_grad():
    B, D, V = 4, 5, 10
    x = rng.randn(B, D).astype("float64")
    lab = rng.randint(0, V, (B, 1)).astype("int64")

    def build(v):
        return fluid.layers.hsigmoid(v["x"], v["l"], V)

    (loss,) = run_forward(build, {"x": x, "l": lab})
    assert loss.shape == (B, 1) and (loss > 0).all()
    check_grad(build, {"x": x, "l": lab}, wrt=["x"], rtol=5e-3)


def test_random_layers():
    x = rng.randn(5, 3).astype("float32")
    (g,) = run_forward(
        lambda v: fluid.layers.gaussian_random([4, 6], std=2.0), {"x": x})
    assert g.shape == (4, 6)
    (u,) = run_forward(
        lambda v: fluid.layers.uniform_random_batch_size_like(
            v["x"], [10, 7]), {"x": x})
    assert u.shape == (5, 7) and (u >= -1).all() and (u <= 1).all()
    probs = np.full((6, 4), 0.25, "float32")
    (ids,) = run_forward(lambda v: fluid.layers.sampling_id(v["p"]),
                         {"p": probs})
    assert ids.shape == (6,) and ((ids >= 0) & (ids < 4)).all()
    (rc,) = run_forward(
        lambda v: fluid.layers.random_crop(v["x8"], [5, 5]),
        {"x8": rng.randn(2, 8, 8).astype("float32")})
    assert rc.shape == (2, 5, 5)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_mean_iou():
    pred = np.array([[0, 1, 1, 2]], "int64")
    lab = np.array([[0, 1, 2, 2]], "int64")

    def build(v):
        miou, wrong, correct = fluid.layers.mean_iou(v["p"], v["l"], 3)
        return miou

    (miou,) = run_forward(build, {"p": pred, "l": lab})
    # class ious: 0: 1/1, 1: 1/2, 2: 1/2 → mean 2/3
    np.testing.assert_allclose(float(miou), 2 / 3, rtol=1e-5)


def test_chunk_eval_iob():
    # tags: type*2 + {0:B, 1:I}; "other" type id = num_chunk_types
    # seq: B0 I0 O B1 → chunks (0,1,t0), (3,3,t1)
    O = 4  # 2 chunk types * 2 tags = other
    inf = np.array([[0, 1, O, 2]], "int64")
    lab = np.array([[0, 1, O, 0]], "int64")  # second chunk differs in type

    def build(v):
        p, r, f1, ni, nl, nc = fluid.layers.chunk_eval(
            v["i"], v["l"], "IOB", 2)
        return [p, r, f1, ni, nl, nc]

    p, r, f1, ni, nl, nc = run_forward(build, {"i": inf, "l": lab})
    assert (int(np.asarray(ni).reshape(())) == 2
            and int(np.asarray(nl).reshape(())) == 2
            and int(np.asarray(nc).reshape(())) == 1)
    np.testing.assert_allclose(np.asarray(p).reshape(()), 0.5)
    np.testing.assert_allclose(np.asarray(r).reshape(()), 0.5)
