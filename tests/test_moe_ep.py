"""MoE/EP hardening: switch_moe convergence + StepStats health wiring.

ROADMAP item 2 satellites: the 2-expert convergence bar (the model
actually trains), and the aux-loss / dropped-token fraction riding
StepStats (-> /stepz) and gauges (-> /metrics) whenever they are
fetched under FLAGS_runtime_stats.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import flags as core_flags
from paddle_tpu.core import unique_name
from paddle_tpu.core.executor import Executor, Scope
from paddle_tpu.core.program import Program, program_guard
from paddle_tpu.observability import stats as obs_stats
from paddle_tpu.observability import step_stats as obs_step


def _build_moe(E=2, D=8, d_ffn=16, N=64, lr=1e-2, prefix="moe_t"):
    prog, startup = Program(), Program()
    prog.random_seed = 5
    with program_guard(prog, startup), unique_name.guard():
        x = fluid.layers.data("x", [D])
        y = fluid.layers.data("y", [1], dtype="int64")
        out, aux, dropped = fluid.nets.switch_moe(
            x, num_experts=E, d_ffn=d_ffn, capacity_per_expert=N,
            name_prefix=prefix, return_aux=True)
        logits = fluid.layers.fc(out, 2, act="softmax")
        ce = fluid.layers.mean(fluid.layers.cross_entropy(logits, y))
        loss = fluid.layers.elementwise_add(
            ce, fluid.layers.scale(aux, scale=0.01))
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return prog, startup, loss, aux, dropped


def _moe_batch(N=64, D=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(N, D).astype("float32")
    # two separated clusters with opposite labels: learnable fast, and
    # cluster-specialized experts help the router find structure
    x[: N // 2] += 2.0
    x[N // 2:] -= 2.0
    y = np.zeros((N, 1), "int64")
    y[N // 2:] = 1
    return x, y


def test_switch_moe_2expert_convergence():
    """A 2-expert switch_moe model trains to the loss bar (ROADMAP item
    2 / VERDICT missing #3): cross-entropy drops below 0.1 and well
    below its starting point."""
    N = 64
    prog, startup, loss, aux, dropped = _build_moe(E=2, N=N)
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    x, y = _moe_batch(N)
    losses = []
    for step in range(80):
        (l, a, d) = exe.run(prog, feed={"x": x, "y": y},
                            fetch_list=[loss.name, aux.name, dropped.name],
                            scope=scope)
        losses.append(float(l))
        assert np.isfinite(losses[-1]), f"step {step}: {losses[-1]}"
        # capacity_per_expert=N: nothing can drop
        assert float(np.asarray(d)) == pytest.approx(0.0, abs=1e-6)
        assert float(np.asarray(a)) > 0.0
    assert losses[-1] < 0.1, f"did not converge: {losses[::16]}"
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])


def test_moe_aux_stats_ride_step_stats():
    """Fetching the registered aux vars under FLAGS_runtime_stats lands
    them in the StepStats record (extras -> /stepz) and same-named
    gauges (-> /metrics)."""
    N = 16
    prog, startup, loss, aux, dropped = _build_moe(E=2, N=N,
                                                   prefix="moe_ss")
    assert prog.step_stat_vars[aux.name] == "moe.moe_ss.aux_loss"
    assert prog.step_stat_vars[dropped.name] == "moe.moe_ss.dropped_frac"
    # survives clone/serialize (transpilers clone programs)
    assert prog.clone().step_stat_vars == prog.step_stat_vars

    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    x, y = _moe_batch(N)
    saved = core_flags.get_flags("runtime_stats")
    core_flags.set_flags({"runtime_stats": True})
    try:
        obs_step.clear()
        exe.run(prog, feed={"x": x, "y": y},
                fetch_list=[loss.name, aux.name, dropped.name],
                scope=scope)
        (rec,) = obs_step.last_n(1)
        assert rec.extras is not None
        assert rec.extras["moe.moe_ss.aux_loss"] > 0.0
        assert rec.extras["moe.moe_ss.dropped_frac"] == pytest.approx(
            0.0, abs=1e-6)
        # /stepz JSON export carries the extras
        export = obs_step.recorder().export(tail=1)
        assert export["last"][-1]["extras"][
            "moe.moe_ss.aux_loss"] == rec.extras["moe.moe_ss.aux_loss"]
        # gauges on the metric surface
        snap = obs_stats.snapshot()
        assert any(k.startswith("moe.moe_ss.aux_loss") for k in snap), \
            sorted(k for k in snap if k.startswith("moe"))[:4]
    finally:
        core_flags.set_flags({"runtime_stats": saved})


def test_moe_stats_absent_when_not_fetched():
    """Not fetching the aux vars (or keeping stats off) adds nothing."""
    N = 16
    prog, startup, loss, aux, dropped = _build_moe(E=2, N=N,
                                                   prefix="moe_off")
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    x, y = _moe_batch(N)
    saved = core_flags.get_flags("runtime_stats")
    core_flags.set_flags({"runtime_stats": True})
    try:
        obs_step.clear()
        exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss.name],
                scope=scope)
        (rec,) = obs_step.last_n(1)
        assert rec.extras is None
    finally:
        core_flags.set_flags({"runtime_stats": saved})


def test_step_stat_vars_follow_pipeline_stage_programs():
    """Transpile-only (tier-1): the switch_moe step-stat registration
    follows the aux vars onto the emitted stage programs — fresh-
    Program emission must not drop what clone() keeps."""
    import paddle_tpu.pipeline as pipe
    prog, startup, loss, aux, dropped = _build_moe(E=2, N=8,
                                                   prefix="moe_reg")
    pp = pipe.PipelineTranspiler().transpile(
        prog, startup, num_stages=2, num_microbatches=2,
        loss_name=loss.name)
    regs = {}
    for st in pp.stages:
        for p in (st.fwd_program, st.bwd_program, st.opt_program):
            if p is not None:
                regs.update(p.step_stat_vars)
    assert set(regs.values()) == {"moe.moe_reg.aux_loss",
                                  "moe.moe_reg.dropped_frac"}


@pytest.mark.slow
def test_moe_trains_inside_pipeline_last_stage():
    """Pipeline + MoE compose: a 2-stage pipeline whose last stage holds
    the 2-expert MoE matches the single-process loss curve (the EP and
    PP axes do not fight over the program rewrite)."""
    import paddle_tpu.pipeline as pipe
    N, M, D = 32, 4, 8
    mb = N // M

    def build():
        # capacity sized to the MICROBATCH token count (the pipeline
        # runs the block per microbatch of mb rows); explicit stage
        # markers pin the MoE whole onto stage 1
        prog, startup = Program(), Program()
        prog.random_seed = 5
        with program_guard(prog, startup), unique_name.guard():
            x = fluid.layers.data("x", [D])
            y = fluid.layers.data("y", [1], dtype="int64")
            with fluid.pipeline_stage_guard(0):
                h = fluid.layers.fc(x, D, act="relu")
            with fluid.pipeline_stage_guard(1):
                out, aux, dropped = fluid.nets.switch_moe(
                    h, num_experts=2, d_ffn=16, capacity_per_expert=mb,
                    name_prefix="moe_pp", return_aux=True)
                logits = fluid.layers.fc(out, 2, act="softmax")
                ce = fluid.layers.mean(fluid.layers.cross_entropy(logits,
                                                                  y))
                loss = fluid.layers.elementwise_add(
                    ce, fluid.layers.scale(aux, scale=0.01))
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
        return prog, startup, loss, aux, dropped

    x, y = _moe_batch(N)
    feed = {"x": x, "y": y}

    # reference for the FIRST microbatch's pre-update loss: a fresh
    # single-process run on the same mb rows from the same named init
    prog, startup, loss, aux, dropped = build()
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    (l0,) = exe.run(prog, feed={"x": x[:mb], "y": y[:mb]},
                    fetch_list=[loss.name], scope=scope)

    prog2, startup2, loss2, _, _ = build()
    pp = pipe.PipelineTranspiler().transpile(
        prog2, startup2, num_stages=2, num_microbatches=M,
        loss_name=loss2.name)
    # the MoE (and its optimizer + aux graph) must land whole on the
    # last stage — its params are consumed there
    moe_stage = {pp.op_stage_assignment[i]
                 for i, op in enumerate(prog2.global_block.ops)
                 if "moe_pp" in " ".join(op.input_arg_names())
                 and pp.op_stage_assignment[i] is not None}
    assert moe_stage == {1}, moe_stage
    # the step-stat registration follows the aux vars onto the emitted
    # stage program (fresh-Program emission must not drop it)
    assert set(pp.stages[1].fwd_program.step_stat_vars.values()) == {
        "moe.moe_pp.aux_loss", "moe.moe_pp.dropped_frac"}
    assert not pp.stages[0].fwd_program.step_stat_vars
    tr = pipe.PipelineTrainer(pp).init()
    first = tr.run(feed)
    assert first.microbatch_losses[0] == pytest.approx(float(l0),
                                                       rel=1e-5)
    got = [first.loss] + [tr.run(feed).loss for _ in range(5)]
    assert all(np.isfinite(v) for v in got)
    assert got[-1] < got[0], got
