"""Platform layer + flags system (reference platform/place.h,
device_context.h:200 pool, and the gflags env bootstrap
python/paddle/fluid/__init__.py:112-132)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import unique_name
from paddle_tpu.core.executor import Executor, Scope
from paddle_tpu.core.program import Program, program_guard


def test_places_and_pool():
    p0 = fluid.TPUPlace(0)
    assert p0 == fluid.TPUPlace(0) and p0 != fluid.CPUPlace()
    assert fluid.CUDAPlace is fluid.TPUPlace  # compat alias
    pool = fluid.DeviceContextPool.instance()
    ctx = pool.get(p0)
    assert pool.get(fluid.TPUPlace(0)) is ctx  # keyed by place
    assert ctx.platform  # cpu under tests, tpu on hardware
    ctx.synchronize()
    assert fluid.device_count() >= 1
    assert len(fluid.tpu_places()) == fluid.device_count()


def test_flags_env_types_and_api():
    assert fluid.get_flags("check_nan_inf") is False
    assert fluid.get_flags("FLAGS_benchmark") is False
    multi = fluid.get_flags(["check_nan_inf", "rpc_deadline"])
    assert multi == {"check_nan_inf": False, "rpc_deadline": 120.0}
    fluid.set_flags({"FLAGS_rpc_deadline": "60"})
    assert fluid.get_flags("rpc_deadline") == 60.0
    fluid.set_flags({"rpc_deadline": 120.0})
    with pytest.raises(KeyError):
        fluid.get_flags("no_such_flag")
    with pytest.raises(KeyError):
        fluid.set_flags({"no_such_flag": 1})


def test_check_nan_inf_flag_catches_bad_values():
    prog, startup = Program(), Program()
    with program_guard(prog, startup), unique_name.guard():
        x = fluid.layers.data("x", [2])
        y = fluid.layers.log(x)  # log of a negative → NaN
    exe = Executor()
    scope = Scope()
    exe.run(startup, scope=scope)
    bad = np.array([[-1.0, 2.0]], "float32")
    # off: NaN flows silently (reference default)
    (out,) = exe.run(prog, feed={"x": bad}, fetch_list=[y], scope=scope)
    assert np.isnan(out).any()
    fluid.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError, match="NaN/Inf"):
            exe.run(prog, feed={"x": bad}, fetch_list=[y], scope=scope)
        ok = np.array([[1.0, 2.0]], "float32")
        exe.run(prog, feed={"x": ok}, fetch_list=[y], scope=scope)
    finally:
        fluid.set_flags({"check_nan_inf": False})
def test_check_nan_inf_bf16():
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.executor import Executor, Scope
    from paddle_tpu.core.program import Program, program_guard
    import pytest
    prog, startup = Program(), Program()
    with program_guard(prog, startup), unique_name.guard():
        x = fluid.layers.data("x", [2], dtype="bfloat16")
        y = fluid.layers.log(x)
    exe = Executor(); scope = Scope(); exe.run(startup, scope=scope)
    fluid.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            exe.run(prog, feed={"x": np.array([[-1.0, 2.0]], "float32")},
                    fetch_list=[y], scope=scope)
    finally:
        fluid.set_flags({"check_nan_inf": False})


def test_contrib_introspection_tools():
    import paddle_tpu as fluid
    from paddle_tpu.contrib import memory_usage, op_freq_statistic
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.program import Program, program_guard

    prog, startup = Program(), Program()
    with program_guard(prog, startup), unique_name.guard():
        x = fluid.layers.data("x", [16])
        h = fluid.layers.fc(x, 8, act="relu")
        fluid.layers.fc(h, 2)
    lo, hi, unit = memory_usage(prog, batch_size=32)
    assert 0 < lo < hi and unit in ("B", "KB", "MB", "GB", "TB")
    uni, adj = op_freq_statistic(prog)
    assert uni.get("mul", 0) == 2 and uni.get("relu", 0) == 1
    assert any("->" in k for k in adj)


def test_tools_kube_gen_job_and_timeline(tmp_path):
    """tools/ parity (SURVEY §2.12): the k8s job generator emits the
    PADDLE_* env contract + registry wiring; timeline.py merges span
    dumps with per-input pids."""
    import json
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(here, "tools", "kube_gen_job.py"),
         "--jobname", "t", "--image", "img", "--entry", "python x.py",
         "--registry", "reg:7000", "--outdir", str(tmp_path)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    ps = json.load(open(tmp_path / "pserver.yaml"))
    tn = json.load(open(tmp_path / "trainer.yaml"))
    svc = json.load(open(tmp_path / "service.yaml"))
    envs = {e["name"]: e["value"] for e in
            ps["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert envs["PADDLE_TRAINING_ROLE"] == "PSERVER"
    assert envs["FLAGS_pserver_registry"] == "reg:7000"
    # identity + DNS mechanics: Indexed jobs, headless service subdomain,
    # shell-exported per-pod identity (kubelet can't expand
    # JOB_COMPLETION_INDEX in user env)
    assert ps["spec"]["completionMode"] == "Indexed"
    assert tn["spec"]["completionMode"] == "Indexed"
    assert svc["spec"]["clusterIP"] == "None"
    assert ps["spec"]["template"]["spec"]["subdomain"] == "t-svc"
    ps_cmd = ps["spec"]["template"]["spec"]["containers"][0]["command"][2]
    assert "PADDLE_CURRENT_ENDPOINT=" in ps_cmd
    assert "$JOB_COMPLETION_INDEX" in ps_cmd
    tn_cmd = tn["spec"]["template"]["spec"]["containers"][0]["command"][2]
    assert "PADDLE_TRAINER_ID=" in tn_cmd
    tn_envs = {e["name"]: e["value"] for e in
               tn["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert "t-trainer-0.t-svc:" in tn_envs["PADDLE_TRAINER_ENDPOINTS"]

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    json.dump({"traceEvents": [{"name": "x", "ph": "X", "ts": 0,
                                "dur": 5, "tid": 1}]}, open(a, "w"))
    json.dump({"traceEvents": [{"name": "y", "ph": "X", "ts": 1,
                                "dur": 2, "tid": 1}]}, open(b, "w"))
    out = tmp_path / "tl.json"
    r = subprocess.run(
        [sys.executable, os.path.join(here, "tools", "timeline.py"),
         "--profile_path", f"{a},{b}", "--timeline_path", str(out)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    tl = json.load(open(out))
    assert {e.get("pid") for e in tl["traceEvents"]} == {0, 1}


def test_reference_top_level_compat_names():
    """The reference fluid top-level __all__ resolves completely,
    including the traps: ``fluid.annotations`` must be the module (not
    the __future__ _Feature the import system short-circuits to), and
    learning_rate_decay is the scheduler module under its reference
    spelling."""
    import warnings

    import paddle_tpu as fluid

    assert callable(fluid.annotations.deprecated)

    @fluid.annotations.deprecated("1.0", "new_api")
    def legacy():
        return 7

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert legacy() == 7
        assert any(issubclass(x.category, DeprecationWarning) for x in w)

    assert fluid.learning_rate_decay.exponential_decay is \
        fluid.layers.learning_rate_scheduler.exponential_decay
    assert fluid.LoDTensorArray is list
    assert fluid.CUDAPinnedPlace() == fluid.CUDAPinnedPlace()
    assert fluid.CUDAPinnedPlace() != fluid.CPUPlace()
