"""High-level Trainer/Inferencer + py_reader + transpiler shims
(reference contrib/trainer.py:169,379, contrib/inferencer.py,
layers/io.py:477 py_reader, memory_optimization_transpiler.py)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core import unique_name
from paddle_tpu.core.program import Program, program_guard

L = fluid.layers


def _train_func():
    x = L.data("x", [4])
    y = L.data("y", [1])
    pred = L.fc(x, 1)
    loss = L.mean(L.square_error_cost(pred, y))
    acc = L.mean(pred)
    return [loss, acc]


def _opt_func():
    return fluid.optimizer.SGD(0.05)


def _reader():
    rng = np.random.RandomState(0)
    w = rng.randn(4, 1).astype("float32")
    for _ in range(8):
        x = rng.randn(16, 4).astype("float32")
        yield list(zip(x, (x @ w).astype("float32")))


def test_trainer_events_checkpoints_and_resume(tmp_path):
    ckpt = fluid.CheckpointConfig(str(tmp_path / "ck"), max_num_checkpoints=2)
    events, losses = [], []

    def handler(ev):
        events.append(type(ev).__name__)
        if isinstance(ev, fluid.EndStepEvent):
            losses.append(float(ev.metrics[0]))

    trainer = fluid.Trainer(_train_func, _opt_func, checkpoint_config=ckpt)
    trainer.train(num_epochs=3, event_handler=handler, reader=_reader,
                  feed_order=["x", "y"])
    assert losses[-1] < losses[0]
    assert events[0] == "BeginEpochEvent" and "EndStepEvent" in events
    # max_num_checkpoints retention
    import os
    kept = sorted(os.listdir(ckpt.checkpoint_dir))
    assert kept == ["epoch_1", "epoch_2"]

    trainer.save_params(str(tmp_path / "params"))
    trainer.save_inference_model(str(tmp_path / "inf"), ["x"], [1])

    # a NEW trainer resumes from the latest checkpoint: first-step loss
    # continues from trained params, far below the fresh-init loss
    resumed = fluid.Trainer(_train_func, _opt_func, checkpoint_config=ckpt)
    rlosses = []

    def handler2(ev):
        if isinstance(ev, fluid.EndStepEvent):
            rlosses.append(float(ev.metrics[0]))

    resumed.train(num_epochs=1, event_handler=handler2, reader=_reader,
                  feed_order=["x", "y"])
    assert rlosses[0] < losses[0] * 0.5

    # Inferencer over the saved params
    def _infer_func():
        x = L.data("x", [4])
        return L.fc(x, 1)

    inf = fluid.Inferencer(_infer_func, str(tmp_path / "params"))
    (out,) = inf.infer({"x": np.ones((2, 4), "float32")})
    assert out.shape == (2, 1)


def test_trainer_stop_event():
    trainer = fluid.Trainer(_train_func, _opt_func)
    seen = []

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            seen.append(ev.step)
            if ev.step >= 2:
                trainer.stop()

    trainer.train(num_epochs=5, event_handler=handler, reader=_reader,
                  feed_order=["x", "y"])
    assert max(seen) == 2  # stopped mid-epoch


def test_py_reader_round_trip():
    prog, startup = Program(), Program()
    with program_guard(prog, startup), unique_name.guard():
        reader = L.io.py_reader(capacity=4, shapes=[(-1, 3), (-1, 1)],
                                dtypes=["float32", "float32"], name="r")
        x, y = L.io.read_file(reader)
        loss = L.mean(L.elementwise_add(x, y))

    def source():
        for i in range(5):
            xs = np.full((4, 3), float(i), "float32")
            ys = np.full((4, 1), 1.0, "float32")
            yield list(zip(xs, ys))

    reader.decorate_paddle_reader(source)
    from paddle_tpu.core.executor import Executor, Scope
    exe = Executor()
    scope = Scope()
    exe.run(startup, scope=scope)
    vals = []
    for feed in reader.start():
        (lv,) = exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)
        vals.append(float(lv))
    assert len(vals) == 5
    np.testing.assert_allclose(vals, [1, 2, 3, 4, 5])


def test_transpiler_shims():
    prog, startup = Program(), Program()
    with program_guard(prog, startup), unique_name.guard():
        x = L.data("x", [4])
        h = L.fc(x, 8, act="relu")
    n_ops = len(prog.global_block.ops)
    assert fluid.memory_optimize(prog) is prog      # no-op, same program
    assert fluid.release_memory(prog) is prog
    from paddle_tpu.core.executor import Executor, Scope, scope_guard
    scope = Scope()
    with scope_guard(scope):
        Executor().run(startup)
        fluid.InferenceTranspiler().transpile(prog, scope=scope)
    types = [op.type for op in prog.global_block.ops]
    assert "fused_fc" in types and len(types) < n_ops


def test_checkpoint_resume_numbering_keeps_freshest(tmp_path):
    """Regression: a resumed trainer numbers checkpoints AFTER the loaded
    epoch, so retention never deletes the just-saved resume checkpoint."""
    import os
    ckpt = fluid.CheckpointConfig(str(tmp_path / "ck"), max_num_checkpoints=2)
    t1 = fluid.Trainer(_train_func, _opt_func, checkpoint_config=ckpt)
    t1.train(3, lambda ev: None, reader=_reader, feed_order=["x", "y"])
    assert sorted(os.listdir(ckpt.checkpoint_dir)) == ["epoch_1", "epoch_2"]
    t2 = fluid.Trainer(_train_func, _opt_func, checkpoint_config=ckpt)
    t2.train(1, lambda ev: None, reader=_reader, feed_order=["x", "y"])
    assert sorted(os.listdir(ckpt.checkpoint_dir)) == ["epoch_2", "epoch_3"]


def test_py_reader_tensor_provider_mode():
    prog, startup = Program(), Program()
    with program_guard(prog, startup), unique_name.guard():
        reader = L.io.py_reader(capacity=2, shapes=[(-1, 3)],
                                dtypes=["float32"])
        x = L.io.read_file(reader)
        s = L.mean(x)

    def tensor_source():
        for i in range(3):
            yield [np.full((2, 3), float(i), "float32")]

    reader.decorate_tensor_provider(tensor_source)
    from paddle_tpu.core.executor import Executor, Scope
    exe = Executor()
    scope = Scope()
    exe.run(startup, scope=scope)
    got = [float(exe.run(prog, feed=fd, fetch_list=[s], scope=scope)[0])
           for fd in reader.start()]
    assert got == [0.0, 1.0, 2.0]


def test_trainer_save_train_model_handoff(tmp_path):
    """Trainer.save_train_model exports the native-trainable layout:
    another process (Python here; the C trainer in test_capi_train.py)
    loads it and CONTINUES training from the same state."""
    from paddle_tpu.contrib.trainer import Trainer
    from paddle_tpu.core.executor import Executor, Scope, scope_guard

    t = Trainer(train_func=_train_func, optimizer_func=_opt_func)

    def handler(event):
        pass

    t.train(num_epochs=1, event_handler=handler, reader=_reader,
            feed_order=["x", "y"])
    out = str(tmp_path / "handoff")
    t.save_train_model(out, ["x", "y"])
    trained = {p.name: np.asarray(t.scope.find_var(p.name))
               for p in t.train_program.all_parameters()}

    scope, exe = Scope(), Executor()
    with scope_guard(scope):
        main, startup, feeds, loss = fluid.io.load_train_model(out, exe)
        assert feeds == ["x", "y"]
        exe.run(startup)
        fluid.io.load_persistables(exe, out, main)
        # the restore is bit-exact: loaded params == the Trainer's
        # trained state, not a re-init
        for name, want in trained.items():
            np.testing.assert_array_equal(
                np.asarray(scope.find_var(name)), want, err_msg=name)
        rng = np.random.RandomState(1)
        w = rng.randn(4, 1).astype("float32")
        losses = []
        for _ in range(6):
            x = rng.randn(16, 4).astype("float32")
            l, = exe.run(main, feed={"x": x, "y": (x @ w).astype("float32")},
                         fetch_list=[loss], sync=True)
            losses.append(float(np.asarray(l)))
    # and continued training keeps optimizing (no blowup)
    assert losses[-1] < losses[0] * 1.5
