"""Compare the live public API against the frozen spec.

Reference role: ``tools/diff_api.py`` — CI fails when the public surface
drifts without the spec being updated on purpose.

Usage: python tools/diff_api.py [spec_path]
Exit 0 when identical; exit 1 with a readable diff otherwise.  To accept
an intentional change: python tools/print_signatures.py > tools/api_spec.txt
"""
from __future__ import annotations

import difflib
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    spec_path = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(HERE, "api_spec.txt")
    sys.path.insert(0, os.path.dirname(HERE))  # repo root: paddle_tpu
    sys.path.insert(0, HERE)                   # tools/: print_signatures
    from print_signatures import iter_api  # noqa: E402

    want = open(spec_path).read().splitlines()
    got = sorted(set(iter_api()))
    if want == got:
        print("API surface matches the frozen spec "
              f"({len(got)} records)")
        return 0
    diff = list(difflib.unified_diff(want, got, "api_spec.txt", "live API",
                                     lineterm=""))
    print("\n".join(diff[:200]))
    print(f"\nAPI drift: {sum(1 for l in diff if l.startswith('+') and not l.startswith('+++'))} added, "
          f"{sum(1 for l in diff if l.startswith('-') and not l.startswith('---'))} removed/changed.")
    print("If intentional: python tools/print_signatures.py > tools/api_spec.txt")
    return 1


if __name__ == "__main__":
    sys.exit(main())
