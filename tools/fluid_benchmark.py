"""Unified training benchmark driver (reference
benchmark/fluid/fluid_benchmark.py:310 + args.py — same CLI contract,
clean-room implementation over the paddle_tpu stack).

    python tools/fluid_benchmark.py --model mnist --batch_size 64 \\
        --iterations 20 [--parallel] [--update_method local|pserver|nccl2]

- ``local``: single Executor, or ParallelExecutor over all local devices
  with ``--parallel``.
- ``pserver``: the DistributeTranspiler path; role/topology from the
  PADDLE_* env vars (PADDLE_TRAINING_ROLE, PADDLE_PSERVER_ENDPOINTS,
  PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM) — the reference
  ``dist_transpile:63`` contract.
- ``nccl2``: every process joins one global mesh via jax.distributed
  (PADDLE_TRAINER_ENDPOINTS), ParallelExecutor runs the same program
  everywhere — the reference ``append_nccl2_prepare:31`` role.

Feeds are synthetic at the requested batch size (the reference's
--use_fake_data mode); throughput prints per iteration window with the
first ``--skip_batch_num`` iterations excluded, matching the reference's
reporting.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mnist(args):
    from paddle_tpu.models import mnist

    feeds, loss, _ = mnist.build(lr=args.learning_rate)
    rng = np.random.RandomState(7)

    def feed(i):
        return {"pixel": rng.randn(args.batch_size, 1, 28, 28)
                .astype("float32"),
                "label": rng.randint(0, 10, (args.batch_size, 1))
                .astype("int64")}
    return feed, loss


def _resnet(args):
    from paddle_tpu.models import resnet

    layout = "NHWC" if args.data_format == "NHWC" else "NCHW"
    feeds, loss, _ = resnet.build(dtype="float32", lr=args.learning_rate,
                                  layout=layout)
    rng = np.random.RandomState(7)

    def feed(i):
        return {"data": rng.randn(args.batch_size, 3, 224, 224)
                .astype("float32"),
                "label": rng.randint(0, 1000, (args.batch_size, 1))
                .astype("int64")}
    return feed, loss


def _vgg(args):
    from paddle_tpu.models import vgg

    feeds, loss, _ = vgg.build(lr=args.learning_rate)
    rng = np.random.RandomState(7)

    def feed(i):
        return {"data": rng.randn(args.batch_size, 3, 32, 32)
                .astype("float32"),
                "label": rng.randint(0, 10, (args.batch_size, 1))
                .astype("int64")}
    return feed, loss


def _stacked_lstm(args):
    from paddle_tpu.models import stacked_lstm

    feeds, loss, _ = stacked_lstm.build(lr=args.learning_rate)
    rng = np.random.RandomState(7)
    T = 128

    def feed(i):
        return {"words": rng.randint(0, 30000, (args.batch_size, T, 1))
                .astype("int64"),
                "words@LEN": np.full((args.batch_size,), T, "int64"),
                "label": rng.randint(0, 2, (args.batch_size, 1))
                .astype("int64")}
    return feed, loss


def _transformer(args):
    from paddle_tpu.models import transformer

    T, V = 256, 32000
    feeds, loss, _ = transformer.build(src_vocab=V, tgt_vocab=V, max_len=T,
                                       dropout=0.1)
    rng = np.random.RandomState(7)
    mask = np.ones((args.batch_size, T), "float32")

    def feed(i):
        ids = lambda: rng.randint(0, V, (args.batch_size, T)).astype("int64")
        return {"src_ids": ids(), "tgt_ids": ids(), "lbl_ids": ids(),
                "src_mask": mask, "tgt_mask": mask}
    return feed, loss


def _deepfm(args):
    from paddle_tpu.models import deepfm

    rows = int(1e6)
    feeds, loss, _ = deepfm.build(sparse_dim=rows, lr=args.learning_rate)
    rng = np.random.RandomState(7)

    def feed(i):
        return {"dense": rng.randn(args.batch_size, 13).astype("float32"),
                "sparse": rng.randint(0, rows, (args.batch_size, 26))
                .astype("int64"),
                "label": rng.randint(0, 2, (args.batch_size, 1))
                .astype("float32")}
    return feed, loss


BENCHMARK_MODELS = {
    "mnist": _mnist,
    "resnet": _resnet,
    "vgg": _vgg,
    "stacked_lstm": _stacked_lstm,
    "transformer": _transformer,
    "deepfm": _deepfm,
}


def parse_args(argv=None):
    p = argparse.ArgumentParser("fluid_benchmark")
    p.add_argument("--model", choices=sorted(BENCHMARK_MODELS), default="resnet")
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--learning_rate", type=float, default=0.001)
    p.add_argument("--skip_batch_num", type=int, default=5)
    p.add_argument("--iterations", type=int, default=80)
    p.add_argument("--pass_num", type=int, default=1)
    p.add_argument("--data_format", choices=["NCHW", "NHWC"], default="NCHW")
    p.add_argument("--device", choices=["CPU", "GPU", "TPU"], default="TPU",
                   help="GPU accepted for reference-CLI parity; JAX owns "
                        "actual placement")
    p.add_argument("--parallel", action="store_true",
                   help="ParallelExecutor over all local devices")
    p.add_argument("--update_method", default="local",
                   choices=["local", "pserver", "nccl2"])
    p.add_argument("--no_random", action="store_true")
    p.add_argument("--async_mode", action="store_true",
                   help="pserver update_method only: async (no batch "
                        "barriers) instead of the default sync mode")
    a = p.parse_args(argv)
    if a.iterations < 1:
        p.error("--iterations must be >= 1")
    a.sync_mode = not a.async_mode
    return a


def dist_transpile(trainer_id, args, train_prog, startup_prog):
    """reference fluid_benchmark.py dist_transpile:63 — env-driven."""
    import paddle_tpu as fluid

    pserver_eps = os.environ["PADDLE_PSERVER_ENDPOINTS"]
    trainers = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=trainer_id, program=train_prog,
                pservers=pserver_eps, trainers=trainers,
                sync_mode=args.sync_mode, startup_program=startup_prog)
    role = os.environ.get("PADDLE_TRAINING_ROLE", "TRAINER")
    if role == "PSERVER":
        ep = os.environ["PADDLE_CURRENT_ENDPOINT"]
        return t.get_pserver_program(ep), t.get_startup_program(ep), role
    return t.get_trainer_program(), startup_prog, role


def main(argv=None):
    args = parse_args(argv)
    for k, v in sorted(vars(args).items()):
        print(f"{k}: {v}")

    import jax

    if args.device == "CPU":
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import Executor, Scope, scope_guard
    from paddle_tpu.core.program import Program, program_guard
    from paddle_tpu.core import unique_name

    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if args.update_method == "nccl2":
        from paddle_tpu.parallel import init_from_env

        trainer_id, _ = init_from_env()

    train_prog, startup_prog = Program(), Program()
    if args.no_random:
        train_prog.random_seed = 1
    with program_guard(train_prog, startup_prog), unique_name.guard():
        feed_fn, loss = BENCHMARK_MODELS[args.model](args)

    scope = Scope()
    with scope_guard(scope):
        if args.update_method == "pserver":
            prog, startup, role = dist_transpile(trainer_id, args,
                                                 train_prog, startup_prog)
            exe = Executor()
            exe.run(startup)
            if role == "PSERVER":
                exe.run(prog)          # serves until trainers complete
                return
            from paddle_tpu.distributed import wait_server_ready

            wait_server_ready(os.environ["PADDLE_PSERVER_ENDPOINTS"]
                              .split(","))
            run = lambda fd: exe.run(prog, feed=fd, fetch_list=[loss])
        elif args.parallel or args.update_method == "nccl2":
            exe = Executor()
            exe.run(startup_prog)
            pe = fluid.ParallelExecutor(loss_name=loss.name,
                                        main_program=train_prog, scope=scope)
            run = lambda fd: pe.run(feed=fd, fetch_list=[loss])
        else:
            exe = Executor()
            exe.run(startup_prog)
            run = lambda fd: exe.run(train_prog, feed=fd, fetch_list=[loss])

        # the timing window must open at least once even when skip >=
        # iterations (then the last iteration is the measured one)
        skip = min(args.skip_batch_num, args.iterations - 1)
        for pass_id in range(args.pass_num):
            last = None
            t0 = None
            for i in range(args.iterations):
                if i == skip:
                    if last is not None:
                        float(np.asarray(last))  # sync before the window
                    t0 = time.perf_counter()
                (last,) = run(feed_fn(i))
            loss_v = float(np.asarray(last))     # syncs the async queue
            counted = args.iterations - skip
            dt = time.perf_counter() - t0
            eps = args.batch_size * counted / dt if dt > 0 else float("nan")
            print(f"Pass: {pass_id}, Loss: {loss_v:.6f}, "
                  f"Speed: {eps:.2f} examples/sec")
        if args.update_method == "pserver":
            from paddle_tpu.distributed import notify_complete

            notify_complete(
                os.environ["PADDLE_PSERVER_ENDPOINTS"].split(","),
                trainer_id=trainer_id)


if __name__ == "__main__":
    main()
