#!/usr/bin/env python
"""Operate a self-healing fleet from the CLI (the supervisor's surface).

Launch a fleet from a declarative spec file and let the supervisor own
worker lifecycle — replace dead workers from the newest COMPLETE
checkpoint, hold on crash loops, drain on shrink::

    python tools/fleet.py launch fleet.json
    python tools/fleet.py launch fleet.json --debug-port 8080

Administer a RUNNING fleet through its debug server's ``/fleetz`` page
(the launch above with ``--debug-port``)::

    python tools/fleet.py status  127.0.0.1:8080
    python tools/fleet.py resize  127.0.0.1:8080 pserver 3
    python tools/fleet.py drain   127.0.0.1:8080 serving-2
    python tools/fleet.py resume  127.0.0.1:8080 [role]
    python tools/fleet.py cut     127.0.0.1:8080 [--wait 30]

Spec file format (JSON; see ``FleetSpec.from_dict``)::

    {
      "name": "train",
      "registry": "auto",
      "checkpoint_root": "/ckpt/run1",
      "rollback_roles": ["pserver", "trainer"],
      "hysteresis": 2,
      "roles": {
        "pserver": {"count": 2, "logical": "auto",
                    "health_role": "PSERVER",
                    "argv": ["python", "worker.py"],
                    "env": {"PADDLE_CURRENT_ENDPOINT": "{logical}",
                            "PADDLE_BIND_ENDPOINT": "127.0.0.1:0",
                            "FLAGS_pserver_registry": "{registry}"},
                    "restart_budget": 3},
        "trainer": {"count": 1, "after": ["pserver"], "done_ok": true,
                    "argv": ["python", "trainer.py"],
                    "env": {"DIST_START_STEP": "{resume_step}"}}
      }
    }
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
import urllib.parse
import urllib.request

__all__ = ["build_parser", "fleetz_request", "main"]

# runnable as `python tools/fleet.py` from anywhere
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fleet.py",
        description="launch / administer a supervised self-healing fleet")
    sub = p.add_subparsers(dest="cmd", required=True)

    launch = sub.add_parser("launch", help="launch a fleet from a spec "
                                           "file and supervise it")
    launch.add_argument("spec", help="FleetSpec JSON file")
    launch.add_argument("--debug-port", type=int, default=0,
                        help="serve /fleetz (and the rest of the debug "
                             "plane) on this HTTP port")
    launch.add_argument("--poll-s", type=float, default=0.2,
                        help="control-loop tick (default %(default)s)")
    launch.add_argument("--timeout", type=float, default=0.0,
                        help="give up after this many seconds "
                             "(0 = run until done/HOLD/signal)")

    for name, args, help_str in (
            ("status", (), "print a running fleet's /fleetz card"),
            ("resize", ("role", "count"),
             "retarget a role's worker count (stateless grow/drain, or "
             "cut-then-rollback for rollback roles)"),
            ("drain", ("worker",), "gracefully drain one worker"),
            ("resume", (), "lift a crash-loop HOLD"),
            ("cut", (), "trigger a fleet checkpoint cut")):
        sp = sub.add_parser(name, help=help_str)
        sp.add_argument("endpoint", help="debug server host:port of the "
                                         "supervising process")
        for a in args:
            sp.add_argument(a)
        if name == "resume":
            sp.add_argument("role", nargs="?", default="all")
        if name == "cut":
            sp.add_argument("--wait", type=float, default=0.0,
                            help="poll the two-phase commit this long")
        sp.add_argument("--fleet", default=None,
                        help="fleet name when several run in one process")
    return p


def fleetz_request(endpoint: str, params: dict, timeout: float = 30.0):
    """One GET against ``http://endpoint/fleetz`` (the admin surface)."""
    query = urllib.parse.urlencode(
        {k: v for k, v in params.items() if v is not None})
    url = f"http://{endpoint}/fleetz" + (f"?{query}" if query else "")
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:  # error payloads are JSON too
        return json.loads(e.read().decode("utf-8"))


def _launch(args) -> int:
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.distributed.supervisor import FleetSpec, Supervisor
    from paddle_tpu.observability import debug_server

    spec = FleetSpec.from_file(args.spec)
    if args.debug_port:
        _flags.set_flags({"debug_server_port": args.debug_port})
        debug_server.start(port=args.debug_port)
    sup = Supervisor(spec, poll_s=args.poll_s).start()
    print(f"[fleet] {spec.name!r} up: registry {sup.registry_ep}, roles "
          + ", ".join(f"{r}x{s.count}" for r, s in spec.roles.items()),
          flush=True)

    stop = {"sig": None}

    def _on_signal(signum, frame):
        stop["sig"] = signum

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)
    deadline = time.monotonic() + args.timeout if args.timeout else None
    verdict = None
    try:
        while stop["sig"] is None:
            verdict = sup.wait(timeout=1.0)
            if verdict in ("done", "hold"):
                break
            if deadline is not None and time.monotonic() >= deadline:
                verdict = "timeout"
                break
    finally:
        status = sup.status()
        sup.stop()
    print(json.dumps(status, indent=2, default=repr))
    if stop["sig"] is not None:
        print(f"[fleet] stopped on signal {stop['sig']}", flush=True)
        return 0
    print(f"[fleet] verdict: {verdict}", flush=True)
    return {"done": 0, "hold": 3, "timeout": 4}.get(verdict, 1)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "launch":
        return _launch(args)
    params = {"fleet": args.fleet}
    if args.cmd == "resize":
        params["resize"] = f"{args.role}:{args.count}"
    elif args.cmd == "drain":
        params["drain"] = args.worker
    elif args.cmd == "resume":
        params["resume"] = args.role
    elif args.cmd == "cut":
        params["cut"] = "1"
        if args.wait:
            params["wait"] = str(args.wait)
    out = fleetz_request(args.endpoint, params)
    print(json.dumps(out, indent=2, default=repr))
    if args.cmd == "status" and isinstance(out, dict):
        _print_role_table(out)
    return 2 if isinstance(out, dict) and "error" in out else 0


def _print_role_table(out: dict) -> None:
    """Per-role summary under the JSON card: liveness, SLO breaches,
    — when replicas publish capacity (FLAGS_capacity_attribution) —
    the tightest replica's headroom next to the SLO column, — when
    the golden canary runs (FLAGS_canary_probe) — the worst live
    canary-fail streak (`-` = all replicas passing), and — when
    replicas publish memory (FLAGS_memory_attribution) — the tightest
    replica's measured byte headroom (`leak!` = a refcount audit
    failed somewhere in the role)."""
    fleets = out if all(isinstance(v, dict) and "roles" in v
                        for v in out.values()) and out else {"": out}
    for fname, status in fleets.items():
        roles = status.get("roles")
        if not isinstance(roles, dict) or not roles:
            continue
        slo = status.get("slo_breaches") or {}
        print()
        title = f"fleet {status.get('fleet', fname) or fname}"
        print(f"{title}  [{status.get('state', '?')}]")
        print("{:<14}{:>7}{:>8}{:>8}{:>12}{:>11}{:>9}{:>9}".format(
            "role", "count", "target", "hold", "slo_breach", "headroom",
            "canary", "mem"))
        for r in sorted(roles):
            rs = roles[r]
            n_slo = sum(1 for w in slo if str(w).startswith(f"{r}-"))
            hr = rs.get("headroom_frac")
            streak = rs.get("canary_fail_streak")
            mem = rs.get("memory_headroom_frac")
            if rs.get("memory_leak"):
                mem_cell = "leak!"
            elif isinstance(mem, (int, float)):
                mem_cell = f"{mem:.1%}"
            else:
                mem_cell = "-"
            print("{:<14}{:>7}{:>8}{:>8}{:>12}{:>11}{:>9}{:>9}".format(
                r, rs.get("count", "?"), rs.get("target", "?"),
                "yes" if rs.get("hold") else "-",
                n_slo or "-",
                f"{hr:.1%}" if isinstance(hr, (int, float)) else "-",
                f"fail:{streak}" if streak else "-",
                mem_cell))


if __name__ == "__main__":
    sys.exit(main())
