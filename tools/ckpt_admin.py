"""Operator CLI for sharded checkpoint roots (paddle_tpu/checkpoint/).

Inspect and maintain a checkpoint directory from the command line — the
companion to ``tools/cache_admin.py`` for the training-state store:

    python tools/ckpt_admin.py ls       /path/to/ckpt
    python tools/ckpt_admin.py describe /path/to/ckpt [--step N]
    python tools/ckpt_admin.py verify   /path/to/ckpt [--step N] [--deep]
    python tools/ckpt_admin.py prune    /path/to/ckpt --keep 3 [--reap-tmp]

``ls`` prints one line per step — COMPLETE steps (committed manifest)
and in-flight ``_tmp`` residue (writers landed so far vs expected).
``describe`` dumps a step's manifest summary: topology, writers, vars
with global shapes and shard extents.  ``verify`` checks every shard
FILE digest against the manifest (exit 1 on the first mismatch);
``--deep`` additionally verifies every shard ARRAY digest (requires
numpy).  ``prune`` keeps the newest N COMPLETE steps and optionally
reaps in-flight residue.

Everything except ``verify --deep`` is stdlib-only (the manifest is
JSON, file digests are crc32): the CLI runs on any host that can see
the checkpoint directory — a storage box with no numpy/jax included.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import zlib

__all__ = ["list_steps", "describe_step", "verify_files", "prune_root",
           "main"]

# kept in sync with paddle_tpu/checkpoint/store.py (the CLI must not
# import paddle_tpu — stdlib-only contract)
STEP_RE = re.compile(r"^step_(\d{8})$")
TMP_SUBDIR = "_tmp"
MANIFEST_NAME = "MANIFEST.json"


def _manifest_path(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step:08d}", MANIFEST_NAME)


def _load_manifest(root: str, step: int) -> dict:
    with open(_manifest_path(root, step), encoding="utf-8") as f:
        return json.load(f)


def _scan(root: str):
    """(complete_steps, inflight: {step: [writers...]}) under root."""
    complete = []
    if os.path.isdir(root):
        for fn in os.listdir(root):
            m = STEP_RE.match(fn)
            if m and os.path.isfile(os.path.join(root, fn, MANIFEST_NAME)):
                complete.append(int(m.group(1)))
    inflight = {}
    tmp = os.path.join(root, TMP_SUBDIR)
    if os.path.isdir(tmp):
        for fn in os.listdir(tmp):
            m = STEP_RE.match(fn)
            if not m:
                continue
            writers = []
            for p in sorted(os.listdir(os.path.join(tmp, fn))):
                if p.startswith("manifest-") and p.endswith(".json"):
                    writers.append(p[len("manifest-"):-len(".json")])
            inflight[int(m.group(1))] = writers
    return sorted(complete), inflight


def _dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for fn in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, fn))
            except OSError:
                pass
    return total


def list_steps(root: str):
    """One record per step (both COMPLETE and in-flight)."""
    complete, inflight = _scan(root)
    out = []
    for s in complete:
        man = _load_manifest(root, s)
        sdir = os.path.join(root, f"step_{s:08d}")
        out.append({
            "step": s, "state": "COMPLETE",
            "writers": man.get("writers", []),
            "vars": len({sh["var"] for sh in man.get("shards", [])}),
            "bytes": _dir_bytes(sdir),
            "age_s": round(time.time()
                           - os.path.getmtime(sdir), 1),
            "topology": (man.get("topology") or {}).get("kind", "?"),
        })
    for s, writers in sorted(inflight.items()):
        expected = None
        for w in writers:
            try:
                with open(os.path.join(root, TMP_SUBDIR, f"step_{s:08d}",
                                       f"manifest-{w}.json"),
                          encoding="utf-8") as f:
                    expected = json.load(f).get("expected_writers")
                if expected:
                    break
            except (OSError, ValueError):
                continue
        out.append({"step": s, "state": "in-flight",
                    "writers": writers,
                    "expected_writers": expected})
    return out


def describe_step(root: str, step=None) -> dict:
    complete, _ = _scan(root)
    if step is None:
        if not complete:
            raise SystemExit(f"no COMPLETE step under {root!r}")
        step = complete[-1]
    if step not in complete:
        raise SystemExit(
            f"step {step} is not COMPLETE under {root!r} "
            f"(complete: {complete})")
    man = _load_manifest(root, step)
    vars_out = {}
    for sh in man.get("shards", []):
        ent = vars_out.setdefault(sh["var"], {
            "global_shape": sh["global_shape"], "dtype": sh["dtype"],
            "shards": []})
        ent["shards"].append(
            {"writer": sh["writer"],
             "rows": ("replicated" if sh["offset"] is None else
                      [sh["offset"], sh["offset"] + sh["shape"][0]])})
    return {"step": step, "topology": man.get("topology"),
            "writers": man.get("writers"),
            "files": man.get("files"), "vars": vars_out}


def verify_files(root: str, step=None, deep: bool = False) -> dict:
    """File-digest verification (stdlib); ``deep`` adds per-array
    digests via numpy.  Returns a summary; raises SystemExit(1) with a
    message naming the first corrupt file/var."""
    complete, _ = _scan(root)
    steps = complete if step is None else [step]
    checked = {"steps": [], "files": 0, "arrays": 0}
    for s in steps:
        if s not in complete:
            raise SystemExit(f"step {s} is not COMPLETE under {root!r}")
        man = _load_manifest(root, s)
        sdir = os.path.join(root, f"step_{s:08d}")
        for fn, info in sorted((man.get("files") or {}).items()):
            path = os.path.join(sdir, fn)
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError as e:
                raise SystemExit(
                    f"CORRUPT step {s}: cannot read {path!r}: {e}")
            got = "crc32:%08x" % (zlib.crc32(data) & 0xFFFFFFFF)
            if info.get("digest") and got != info["digest"]:
                raise SystemExit(
                    f"CORRUPT step {s}: {path!r} digest mismatch "
                    f"(manifest {info['digest']}, file {got})")
            checked["files"] += 1
        if deep:
            import numpy as np
            by_file = {}
            for sh in man.get("shards", []):
                by_file.setdefault(sh["file"], []).append(sh)
            for fn, shards in sorted(by_file.items()):
                with np.load(os.path.join(sdir, fn)) as data:
                    for sh in shards:
                        arr = np.ascontiguousarray(data[sh["key"]])
                        got = "crc32:%08x" % (
                            zlib.crc32(arr.tobytes()) & 0xFFFFFFFF)
                        if got != sh["digest"]:
                            raise SystemExit(
                                f"CORRUPT step {s}: var {sh['var']!r} "
                                f"shard {sh['key']!r} in {fn!r} fails "
                                "its content digest")
                        checked["arrays"] += 1
        checked["steps"].append(s)
    return checked


def prune_root(root: str, keep: int, reap_tmp: bool = False) -> dict:
    import shutil
    if keep < 1:
        raise SystemExit("--keep must be >= 1")
    complete, inflight = _scan(root)
    doomed = complete[:-keep] if len(complete) > keep else []
    for s in doomed:
        shutil.rmtree(os.path.join(root, f"step_{s:08d}"),
                      ignore_errors=True)
    reaped = []
    if reap_tmp:
        for s in inflight:
            shutil.rmtree(os.path.join(root, TMP_SUBDIR, f"step_{s:08d}"),
                          ignore_errors=True)
            reaped.append(s)
    return {"removed_steps": doomed, "reaped_inflight": sorted(reaped),
            "kept": complete[-keep:] if complete else []}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="inspect/maintain a sharded checkpoint root")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_ls = sub.add_parser("ls", help="list COMPLETE + in-flight steps")
    p_ls.add_argument("root")
    p_desc = sub.add_parser("describe", help="dump a step's manifest")
    p_desc.add_argument("root")
    p_desc.add_argument("--step", type=int, default=None)
    p_ver = sub.add_parser("verify", help="digest-verify shard files")
    p_ver.add_argument("root")
    p_ver.add_argument("--step", type=int, default=None)
    p_ver.add_argument("--deep", action="store_true",
                       help="also verify per-array digests (needs numpy)")
    p_pr = sub.add_parser("prune", help="keep the newest N steps")
    p_pr.add_argument("root")
    p_pr.add_argument("--keep", type=int, required=True)
    p_pr.add_argument("--reap-tmp", action="store_true",
                      help="also delete in-flight _tmp residue")
    args = ap.parse_args(argv)

    if args.cmd == "ls":
        for rec in list_steps(args.root):
            print(json.dumps(rec, sort_keys=True))
        return 0
    if args.cmd == "describe":
        print(json.dumps(describe_step(args.root, args.step), indent=2,
                         sort_keys=True))
        return 0
    if args.cmd == "verify":
        out = verify_files(args.root, args.step, deep=args.deep)
        print(json.dumps({"ok": True, **out}, sort_keys=True))
        return 0
    if args.cmd == "prune":
        print(json.dumps(prune_root(args.root, args.keep,
                                    reap_tmp=args.reap_tmp),
                         sort_keys=True))
        return 0
    return 2  # pragma: no cover


if __name__ == "__main__":
    sys.exit(main())
