#!/usr/bin/env python
"""Stitch per-worker span snapshots into one Chrome/Perfetto trace.

The fleet half of the distributed-tracing layer
(``paddle_tpu/observability/trace.py``): every process keeps a bounded
span ring; this tool merges several rings into ONE multi-process
timeline where each worker keeps its real ``pid`` and a labeled
process row — a 2-process trainer+pserver step renders as one stitched
trace (client ``send_vars`` spans over the pserver's server/apply
spans, same trace id).

Inputs, mixable:

- snapshot files: the ``TRACE_PULL`` / ``/tracez?raw=1`` JSON form
  (``{"version":1, "pid":..., "spans":[...]}``), e.g. saved with
  ``python tools/dump_metrics.py <port> --tracez --raw > worker.json``;
- chrome-form files (``{"traceEvents": [...]}``, e.g. ``/tracez``
  output or a flight-recorder-adjacent dump) — passed through with
  pids preserved (collisions bumped);
- ``--endpoints host:port,...``: pull live span rings over the
  ``TRACE_PULL`` RPC from any running worker's RPC port (pserver,
  master, registry — every service answers it).

Usage:
    python tools/stitch_trace.py trainer.json pserver.json -o out.json
    python tools/stitch_trace.py --endpoints 10.0.0.7:6174,10.0.0.8:6174 \\
        -o out.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _repo_import():
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(
        __file__)), ".."))


def load_inputs(paths):
    """→ (snapshots {label: snap}, passthrough chrome event lists)."""
    snaps, chrome = {}, []
    for path in paths:
        with open(path) as f:
            data = json.load(f)
        label = os.path.splitext(os.path.basename(path))[0]
        if isinstance(data, dict) and "spans" in data:
            while label in snaps:
                label += "'"
            snaps[label] = data
        elif isinstance(data, dict) and "traceEvents" in data:
            chrome.append(data["traceEvents"])
        elif isinstance(data, list):
            chrome.append(data)
        else:
            raise ValueError(
                f"{path}: neither a span snapshot ('spans') nor a chrome "
                "trace ('traceEvents')")
    return snaps, chrome


def pull_endpoints(endpoints, timeout: float = 5.0):
    """{endpoint: snapshot} over the TRACE_PULL RPC."""
    _repo_import()
    from paddle_tpu.distributed import transport
    from paddle_tpu.observability import aggregate

    client = transport.RPCClient(0)
    out = {}
    for ep in endpoints:
        payload = client._raw_request(ep, transport.TRACE_PULL,
                                      connect_timeout=timeout)
        out[ep] = aggregate.parse_trace_snapshot(payload)
    return out


def stitch(snaps, chrome_event_lists):
    _repo_import()
    from paddle_tpu.observability import trace as _trace

    doc = _trace.stitch_chrome_trace(snaps)
    used = {e.get("pid") for e in doc["traceEvents"] if "pid" in e}
    for evs in chrome_event_lists:
        own = sorted({e["pid"] for e in evs if "pid" in e})
        remap = {}
        for p in own:
            q = p
            while q in used:
                q += 1
            used.add(q)
            remap[p] = q
        for e in evs:
            e = dict(e)
            e.setdefault("tid", 0)
            e["pid"] = remap.get(e.get("pid"), e.get("pid", 0))
            doc["traceEvents"].append(e)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-worker span rings into one Chrome trace")
    ap.add_argument("inputs", nargs="*",
                    help="snapshot (/tracez?raw=1, TRACE_PULL) or "
                         "chrome-form json files")
    ap.add_argument("--endpoints", default="",
                    help="comma-separated worker RPC endpoints to pull "
                         "span rings from live (TRACE_PULL)")
    ap.add_argument("-o", "--out", required=True,
                    help="output Chrome/Perfetto json path")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    if not args.inputs and not args.endpoints:
        ap.error("need input files and/or --endpoints")
    snaps, chrome = load_inputs(args.inputs)
    if args.endpoints:
        pulled = pull_endpoints(
            [e for e in args.endpoints.split(",") if e.strip()],
            timeout=args.timeout)
        for ep, snap in pulled.items():
            snaps[ep] = snap
    doc = stitch(snaps, chrome)
    with open(args.out, "w") as f:
        json.dump(doc, f)
    n_spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    n_procs = len({e.get("pid") for e in doc["traceEvents"]})
    print(f"wrote {args.out}: {n_spans} spans across {n_procs} "
          f"process(es)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
