"""Operator CLI for the persistent compile cache (core/compile_cache.py).

Inspect and maintain a ``FLAGS_compile_cache_dir`` directory from the
command line — the companion to ``tools/dump_metrics.py`` for the
on-disk half of the cache:

    python tools/cache_admin.py ls     /path/to/cache
    python tools/cache_admin.py stat   /path/to/cache
    python tools/cache_admin.py verify /path/to/cache [--deep]
    python tools/cache_admin.py prune  /path/to/cache --max-bytes 1000000
    python tools/cache_admin.py prune  /path/to/cache   # env/default cap

``ls`` prints one line per tier-A entry (key, size, age, last use, the
environment stamp that gates loads); ``stat`` summarizes occupancy
(entries/bytes, tier-B ``xla/`` subdir bytes, oldest/newest use).
``verify`` checks every entry's framing + header and reports
corrupted/truncated files (exit code 1 if any; ``--fix`` deletes them,
``--deep`` additionally unpickles and loads each executable — requires
jax and the paddle_tpu environment).  ``prune`` applies the LRU byte
cap (``--max-bytes`` overrides ``FLAGS_compile_cache_max_bytes`` from
the environment, default 2 GiB).

Everything except ``verify --deep`` is stdlib-only: the entry framing
(MAGIC + u32 header length + JSON header + payload) is parsed locally,
so the CLI runs on any host that can see the cache directory — a
storage box with no jax installed included.
"""
from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import time

__all__ = ["entry_lines", "stat_dir", "verify_dir", "prune_dir", "main"]

# entry framing — kept in sync with paddle_tpu/core/compile_cache.py
# (the header carries format/jax/jaxlib/platform; FORMAT_VERSION gates
# loads at runtime, the CLI only needs the frame)
MAGIC = b"PTCC1\0"
FORMAT_VERSION = 1
ENTRY_SUFFIX = ".ptcc"
_HEADER_LEN = struct.Struct("<I")
_DEFAULT_CAP = 2 << 30


def _read_header(path: str) -> dict:
    """Parse one entry file's framed JSON header and check the payload
    size accounting.  Raises ValueError on any framing problem."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise ValueError("bad magic")
        raw = f.read(_HEADER_LEN.size)
        if len(raw) != _HEADER_LEN.size:
            raise ValueError("truncated header length")
        (hlen,) = _HEADER_LEN.unpack(raw)
        if hlen <= 0 or hlen > 1 << 20:
            raise ValueError(f"implausible header length {hlen}")
        body = f.read(hlen)
        if len(body) != hlen:
            raise ValueError("truncated header")
        hdr = json.loads(body.decode("utf-8"))
        if not isinstance(hdr, dict):
            raise ValueError("header is not an object")
    payload = size - len(MAGIC) - _HEADER_LEN.size - hlen
    if payload < 0 or payload != int(hdr.get("payload_bytes", payload)):
        raise ValueError("truncated entry (payload size mismatch)")
    return hdr


def _list_entries(d: str):
    """[{key, path, bytes, mtime}] oldest-used first (the prune order;
    mtime is touched on every runtime cache hit)."""
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for n in names:
        if not n.endswith(ENTRY_SUFFIX) or n.startswith(".tmp-"):
            continue
        p = os.path.join(d, n)
        try:
            st = os.stat(p)
        except OSError:
            continue  # racing another process's prune
        out.append({"key": n[:-len(ENTRY_SUFFIX)], "path": p,
                    "bytes": st.st_size, "mtime": st.st_mtime})
    out.sort(key=lambda e: e["mtime"])
    return out


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n}B"


def _fmt_age(seconds: float) -> str:
    if seconds < 120:
        return f"{seconds:.0f}s"
    if seconds < 7200:
        return f"{seconds / 60:.0f}m"
    if seconds < 2 * 86400:
        return f"{seconds / 3600:.1f}h"
    return f"{seconds / 86400:.1f}d"


def entry_lines(d):
    """One formatted line per entry, newest-used last (unreadable
    headers are flagged in-line, not fatal)."""
    now = time.time()
    for e in _list_entries(d):
        try:
            hdr = _read_header(e["path"])
            env = (f"jax={hdr.get('jax')} platform={hdr.get('platform')} "
                   f"mode={hdr.get('meta', {}).get('mode', '?')}")
            created = _fmt_age(now - float(hdr.get("created", now)))
        except Exception as exc:
            env = f"UNREADABLE ({exc})"
            created = "?"
        yield (f"{e['key'][:16]}…  {_fmt_bytes(e['bytes']):>10}  "
               f"created {created:>6} ago  "
               f"used {_fmt_age(now - e['mtime']):>6} ago  {env}")


def stat_dir(d):
    entries = _list_entries(d)
    xla_bytes = 0
    xla_files = 0
    for root, _, files in os.walk(os.path.join(d, "xla")):
        for f in files:
            try:
                xla_bytes += os.path.getsize(os.path.join(root, f))
                xla_files += 1
            except OSError:
                pass
    now = time.time()
    out = {
        "dir": d,
        "tier_a_entries": len(entries),
        "tier_a_bytes": sum(e["bytes"] for e in entries),
        "tier_b_xla_files": xla_files,
        "tier_b_xla_bytes": xla_bytes,
    }
    if entries:
        out["oldest_use_age_s"] = round(now - entries[0]["mtime"], 1)
        out["newest_use_age_s"] = round(now - entries[-1]["mtime"], 1)
    return out


def verify_dir(d, deep=False, fix=False):
    """Check every entry's framing, header JSON, size accounting and
    format version; ``deep`` also unpickles + loads the executable the
    way the runtime would (needs the paddle_tpu/jax environment).
    Returns {ok, bad: [{key, error}], fixed}."""
    bad = []
    ok = 0
    for e in _list_entries(d):
        try:
            hdr = _read_header(e["path"])
            if int(hdr.get("format", -1)) != FORMAT_VERSION:
                raise ValueError(
                    f"format {hdr.get('format')} != {FORMAT_VERSION}")
            if deep:
                _deep_verify(e["path"], hdr)
            ok += 1
        except Exception as exc:
            bad.append({"key": e["key"], "error": repr(exc)[:200]})
            if fix:
                try:
                    os.remove(e["path"])
                except OSError:
                    pass
    return {"ok": ok, "bad": bad, "fixed": fix and len(bad) or 0}


def _deep_verify(path: str, hdr: dict) -> None:
    """Load the executable exactly like the runtime would (the only
    jax-dependent corner of this tool)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import pickle

    from paddle_tpu.core import compile_cache as cc
    env = cc.env_info()
    skew = {k: (hdr.get(k), v) for k, v in env.items()
            if hdr.get(k) != v}
    if skew:
        raise ValueError(f"environment skew {skew}")
    _, blob = cc._read_entry(path)
    payload, in_tree, out_tree = pickle.loads(blob)
    from jax.experimental import serialize_executable as se
    se.deserialize_and_load(payload, in_tree, out_tree)


def prune_dir(d, cap=None):
    """Apply the LRU byte cap: delete oldest-used tier-A entries until
    the rest fit.  Stdlib-only (mirrors compile_cache.prune_lru)."""
    if cap is None:
        env = os.environ.get("FLAGS_compile_cache_max_bytes")
        cap = int(env) if env else _DEFAULT_CAP
    # reap stale tmp files from crashed writers (mirrors the runtime:
    # old enough that no live writer is between write and rename)
    now = time.time()
    for n in os.listdir(d):
        if n.startswith(".tmp-"):
            p = os.path.join(d, n)
            try:
                if now - os.stat(p).st_mtime > 3600:
                    os.remove(p)
            except OSError:
                pass
    entries = _list_entries(d)
    total = sum(e["bytes"] for e in entries)
    evicted = []
    for e in entries:
        if not cap or total <= cap:
            break
        try:
            os.remove(e["path"])
        except OSError:
            continue
        total -= e["bytes"]
        evicted.append(e["key"])
    out = stat_dir(d)
    out["evicted"] = evicted
    out["cap"] = cap
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="persistent compile cache admin (ls/stat/verify/prune)")
    ap.add_argument("cmd", choices=("ls", "stat", "verify", "prune"))
    ap.add_argument("dir", help="the FLAGS_compile_cache_dir directory")
    ap.add_argument("--deep", action="store_true",
                    help="verify: also unpickle + load each executable")
    ap.add_argument("--fix", action="store_true",
                    help="verify: delete entries that fail")
    ap.add_argument("--max-bytes", type=int, default=None,
                    help="prune: byte cap (default "
                         "FLAGS_compile_cache_max_bytes env, else 2 GiB)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.dir):
        print(f"not a directory: {args.dir}", file=sys.stderr)
        return 2
    if args.cmd == "ls":
        n = 0
        for line in entry_lines(args.dir):
            print(line)
            n += 1
        if not n:
            print("(no tier-A entries)")
        return 0
    if args.cmd == "stat":
        print(json.dumps(stat_dir(args.dir), indent=2, sort_keys=True))
        return 0
    if args.cmd == "verify":
        res = verify_dir(args.dir, deep=args.deep, fix=args.fix)
        print(json.dumps(res, indent=2, sort_keys=True))
        return 1 if res["bad"] else 0
    if args.cmd == "prune":
        print(json.dumps(prune_dir(args.dir, args.max_bytes), indent=2,
                         sort_keys=True))
        return 0
    return 2  # pragma: no cover


if __name__ == "__main__":
    sys.exit(main())
