#!/usr/bin/env python
"""Generate Kubernetes manifests for pserver-mode distributed training
(reference benchmark/fluid/kube_gen_job.py:65 — emits pserver/trainer
jobs wired through the PADDLE_* env contract).

TPU-native notes: trainers are TPU-VM pods (one JAX process per host;
``parallel/multihost.py`` forms the JAX world from
``PADDLE_TRAINER_ENDPOINTS`` + ``PADDLE_TRAINER_ID``), pservers are CPU
pods serving the framed-TCP transport, and ``FLAGS_pserver_registry``
points every pod at the elastic discovery registry
(``distributed/registry.py``) so a rescheduled pserver pod re-claims its
shard on a new address.

Kubernetes mechanics: both Jobs use Indexed completion mode + a headless
Service + pod ``subdomain``, so pod *i* is resolvable as
``<job>-<i>.<service>`` and knows its identity from the controller-set
``JOB_COMPLETION_INDEX`` env var.  Identity exports
(``PADDLE_CURRENT_ENDPOINT``, ``PADDLE_TRAINER_ID``) happen in the
entrypoint SHELL — the kubelet cannot expand ``$(JOB_COMPLETION_INDEX)``
in user env because the controller appends it after them.

Manifests are plain JSON (a strict YAML subset) — no yaml dependency.

Usage:
    python tools/kube_gen_job.py --jobname mnist-dist --pservers 2 \
        --trainers 4 --image my/image --entry "python train.py" --outdir jobs/
"""
from __future__ import annotations

import argparse
import json
import os


def _env(d):
    return [{"name": k, "value": str(v)} for k, v in d.items()]


def _headless_service(name):
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name},
        "spec": {"clusterIP": "None",
                 "selector": {"paddle-job-svc": name},
                 "ports": [{"port": 1, "name": "placeholder"}]},
    }


def _job(name, svc, replicas, image, command, envs, port=None):
    container = {"name": name, "image": image,
                 "command": ["sh", "-c", command], "env": _env(envs)}
    if port:
        container["ports"] = [{"containerPort": port}]
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name},
        "spec": {
            "parallelism": replicas,
            "completions": replicas,
            "completionMode": "Indexed",
            "template": {
                "metadata": {"labels": {"paddle-job": name,
                                        "paddle-job-svc": svc}},
                "spec": {"restartPolicy": "OnFailure",
                         "subdomain": svc,
                         "containers": [container]},
            },
        },
    }


def gen_job(args):
    svc = f"{args.jobname}-svc"
    ps_job = f"{args.jobname}-pserver"
    tn_job = f"{args.jobname}-trainer"
    # Indexed-Job pod i has hostname <job>-<i>; with subdomain=svc it is
    # resolvable at <job>-<i>.<svc>
    pserver_eps = ",".join(
        f"{ps_job}-{i}.{svc}:{args.ps_port}" for i in range(args.pservers))
    trainer_eps = ",".join(
        f"{tn_job}-{i}.{svc}:{args.coord_port}" for i in range(args.trainers))
    common = {
        "PADDLE_PSERVER_ENDPOINTS": pserver_eps,
        "PADDLE_TRAINERS_NUM": args.trainers,
        "FLAGS_rpc_transport": "native",
    }
    if args.registry:
        common["FLAGS_pserver_registry"] = args.registry

    # identity from the controller-set JOB_COMPLETION_INDEX, exported in
    # the shell (kubelet can't expand it in user env — it is appended
    # AFTER user vars)
    ps_cmd = (f'export PADDLE_CURRENT_ENDPOINT='
              f'"{ps_job}-$JOB_COMPLETION_INDEX.{svc}:{args.ps_port}"; '
              f'{args.entry}')
    tn_cmd = (f'export PADDLE_TRAINER_ID="$JOB_COMPLETION_INDEX"; '
              f'{args.entry}')
    ps = _job(ps_job, svc, args.pservers, args.image, ps_cmd,
              {**common, "PADDLE_TRAINING_ROLE": "PSERVER"},
              port=args.ps_port)
    tn = _job(tn_job, svc, args.trainers, args.image, tn_cmd,
              {**common, "PADDLE_TRAINING_ROLE": "TRAINER",
               # entry 0 is the jax.distributed coordinator
               # (parallel/multihost.py:30)
               "PADDLE_TRAINER_ENDPOINTS": trainer_eps})
    os.makedirs(args.outdir, exist_ok=True)
    paths = {}
    for name, manifest in (("service", _headless_service(svc)),
                           ("pserver", ps), ("trainer", tn)):
        path = os.path.join(args.outdir, f"{name}.yaml")
        with open(path, "w") as f:
            json.dump(manifest, f, indent=2)
        paths[name] = path
    return paths


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="Generate dist job manifests.")
    p.add_argument("--jobname", default="paddle-tpu-job")
    p.add_argument("--pservers", type=int, default=2)
    p.add_argument("--trainers", type=int, default=2)
    p.add_argument("--image", required=True)
    p.add_argument("--entry", required=True,
                   help="training command run in every pod")
    p.add_argument("--ps-port", type=int, default=6174)
    p.add_argument("--coord-port", type=int, default=6175,
                   help="jax.distributed coordinator port on trainer 0")
    p.add_argument("--registry", default="",
                   help="host:port of the discovery registry (optional)")
    p.add_argument("--outdir", default=".")
    return p.parse_args(argv)


if __name__ == "__main__":
    print(gen_job(parse_args()))
