"""Fetch and pretty-print a worker's debug-server pages by port.

Operator companion to ``paddle_tpu/observability/debug_server.py``
(start workers with ``FLAGS_debug_server_port=<port>``):

    python tools/dump_metrics.py 8085                 # metrics + healthz
    python tools/dump_metrics.py 8085 statusz         # one page
    python tools/dump_metrics.py 8085 metrics stepz
    python tools/dump_metrics.py --host 10.0.0.7 8085 healthz
    python tools/dump_metrics.py --grep rpc_ 8085 metrics
    python tools/dump_metrics.py 8085 --tracez        # Chrome trace json
    python tools/dump_metrics.py 8085 --tracez --raw  # span snapshot
    python tools/dump_metrics.py 8085 --flight        # flight recorder
    python tools/dump_metrics.py 8085 --memz          # device memory
    python tools/dump_metrics.py 8085 --profilez      # cost/roofline
    python tools/dump_metrics.py 8085 --memz --text   # human rendering
    python tools/dump_metrics.py 8085 --decodez       # decode engines
    python tools/dump_metrics.py 8085 --sloz          # SLO watchdog
    python tools/dump_metrics.py 8085 --varz --window 600   # history
    python tools/dump_metrics.py 8085 --capacityz     # util + headroom
    python tools/dump_metrics.py 8085 --tenantz --text  # tenant table
    python tools/dump_metrics.py 8085 --canaryz       # canary + audit
    python tools/dump_metrics.py 8085 --canaryz --text  # streak table
    python tools/dump_metrics.py 8085 --allocz        # memory ledger
    python tools/dump_metrics.py 8085 --allocz --text   # pool table
    python tools/dump_metrics.py 8085 --quantz        # int8 calibration

JSON pages (healthz/statusz/stepz) are re-indented; /metrics is passed
through (optionally filtered with ``--grep``) so the output pastes
straight into a Prometheus exposition parser.  ``--tracez`` fetches the
worker's span ring as a directly-loadable Chrome/Perfetto trace (add
``--raw`` for the snapshot form ``tools/stitch_trace.py`` merges);
``--flight`` fetches the live flight-recorder view
(``/tracez?recent=1`` — recent + in-flight spans, log events, step
tail); ``--memz`` / ``--profilez`` pull the perf plane (live
device-memory stats; per-executable XLA cost/memory attribution with
roofline positions), JSON by default, ``--text`` for the human
rendering.  Stdlib only — runs on any host that can reach the port, no
paddle_tpu import needed.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request

DEFAULT_PAGES = ("metrics", "healthz")
KNOWN_PAGES = ("metrics", "healthz", "statusz", "stepz")


def fetch(host: str, port: int, page: str, timeout: float = 5.0) -> str:
    url = f"http://{host}:{port}/{page.lstrip('/')}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def render(page: str, body: str, grep: str = "") -> str:
    if page.strip("/") == "metrics":
        if grep:
            body = "\n".join(l for l in body.splitlines() if grep in l)
            return body + ("\n" if body else "")
        return body
    try:
        return json.dumps(json.loads(body), indent=2, sort_keys=True) + "\n"
    except ValueError:
        return body


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="dump a paddle_tpu worker's debug-server pages")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--grep", default="",
                    help="only /metrics lines containing this substring")
    ap.add_argument("--tracez", action="store_true",
                    help="fetch the span ring as a Chrome trace "
                         "(/tracez) instead of the default pages")
    ap.add_argument("--raw", action="store_true",
                    help="with --tracez: the snapshot form "
                         "(/tracez?raw=1) for tools/stitch_trace.py")
    ap.add_argument("--flight", action="store_true",
                    help="fetch the live flight-recorder view "
                         "(/tracez?recent=1)")
    ap.add_argument("--memz", action="store_true",
                    help="fetch the live device-memory snapshot (/memz)")
    ap.add_argument("--profilez", action="store_true",
                    help="fetch the perf-attribution records + "
                         "rooflines (/profilez)")
    ap.add_argument("--decodez", action="store_true",
                    help="fetch the decode-plane page (/decodez: "
                         "per-engine slots, paged-cache occupancy, "
                         "queue depth, TTFT/TBT tails, goodput, "
                         "phase attribution)")
    ap.add_argument("--sloz", action="store_true",
                    help="fetch the SLO watchdog page (/sloz: rule "
                         "table with live values and breach state)")
    ap.add_argument("--varz", action="store_true",
                    help="fetch the metric-history page (/varz: "
                         "bounded downsampled counter/gauge series)")
    ap.add_argument("--window", type=float, default=None,
                    help="with --varz: only samples younger than this "
                         "many seconds (?window=)")
    ap.add_argument("--capacityz", action="store_true",
                    help="fetch the capacity page (/capacityz: per-"
                         "pipeline phase utilization, operational-law "
                         "service fits, predicted_max_qps + headroom "
                         "with the binding phase named)")
    ap.add_argument("--tenantz", action="store_true",
                    help="fetch the per-tenant usage page (/tenantz: "
                         "top-K heavy-hitter table with requests/rows/"
                         "tokens/device-ms and the `other` rollup)")
    ap.add_argument("--canaryz", action="store_true",
                    help="fetch the correctness page (/canaryz: golden "
                         "canary per-target pass/fail streaks plus the "
                         "divergence-audit digest ring)")
    ap.add_argument("--allocz", action="store_true",
                    help="fetch the memory-attribution page (/allocz: "
                         "per-pool reserved/used/parked ledger, per-"
                         "device PJRT reconciliation with the "
                         "unattributed residual, allocation event ring)")
    ap.add_argument("--quantz", action="store_true",
                    help="fetch the low-precision-serving page (/quantz: "
                         "per-layer int8 calibration scales + clip "
                         "fractions, quantized-matmul launch/fallback "
                         "counters, quantized KV cache dtype + "
                         "bytes/block)")
    ap.add_argument("--text", action="store_true",
                    help="with --memz/--profilez/--capacityz/--tenantz/"
                         "--canaryz/--allocz/--quantz: the human text "
                         "rendering (?text=1) instead of JSON")
    ap.add_argument("port", type=int,
                    help="the worker's FLAGS_debug_server_port")
    ap.add_argument("pages", nargs="*", default=list(DEFAULT_PAGES),
                    help=f"pages to fetch (default: {' '.join(DEFAULT_PAGES)};"
                         f" known: {' '.join(KNOWN_PAGES)})")
    args = ap.parse_args(argv)

    rc = 0
    if args.tracez or args.flight or args.memz or args.profilez or \
            args.decodez or args.sloz or args.varz or \
            args.capacityz or args.tenantz or args.canaryz or \
            args.allocz or args.quantz:
        pages = []
        if args.tracez:
            pages.append("tracez?raw=1" if args.raw else "tracez")
        if args.flight:
            pages.append("tracez?recent=1")
        suffix = "?text=1" if args.text else ""
        if args.memz:
            pages.append("memz" + suffix)
        if args.profilez:
            pages.append("profilez" + suffix)
        if args.decodez:
            pages.append("decodez")
        if args.sloz:
            pages.append("sloz")
        if args.varz:
            pages.append("varz" + (f"?window={args.window:g}"
                                   if args.window else ""))
        if args.capacityz:
            pages.append("capacityz" + suffix)
        if args.tenantz:
            pages.append("tenantz" + suffix)
        if args.canaryz:
            pages.append("canaryz" + suffix)
        if args.allocz:
            pages.append("allocz" + suffix)
        if args.quantz:
            pages.append("quantz" + suffix)
        for page in pages:
            try:
                body = fetch(args.host, args.port, page,
                             timeout=args.timeout)
            except (urllib.error.URLError, OSError) as e:
                print(f"error fetching /{page}: {e}", file=sys.stderr)
                rc = 1
                continue
            sys.stdout.write(body if body.endswith("\n") else body + "\n")
        return rc
    pages = args.pages or list(DEFAULT_PAGES)
    for page in pages:
        header = f"==== {args.host}:{args.port} /{page.strip('/')} ===="
        if len(pages) > 1:
            print(header)
        try:
            body = fetch(args.host, args.port, page, timeout=args.timeout)
        except (urllib.error.URLError, OSError) as e:
            print(f"error fetching /{page.strip('/')}: {e}", file=sys.stderr)
            rc = 1
            continue
        sys.stdout.write(render(page, body, grep=args.grep))
    return rc


if __name__ == "__main__":
    sys.exit(main())
