"""Operator CLI for the fault-injection plane: arm/list/clear faults on
a LIVE fleet through each worker's debug server (``/chaosz``).

Start workers with ``FLAGS_debug_server_port=<port>`` (the PR-2
observability plane), then:

    # arm a 30%-barrier-drop flap on two pservers for 10 seconds
    python tools/chaos.py --endpoints 127.0.0.1:8085,127.0.0.1:8086 \
        inject 'drop_conn:batch_barrier:p=0.3,for_s=10'

    # kill the primary pserver after its 5th applied round
    python tools/chaos.py --endpoints 127.0.0.1:8085 \
        inject 'kill_after:apply_round:n=5'

    # what's armed where?
    python tools/chaos.py --endpoints 127.0.0.1:8085,127.0.0.1:8086 list

    # stand the fleet back up
    python tools/chaos.py --endpoints 127.0.0.1:8085,127.0.0.1:8086 clear

Rule grammar is documented in ``paddle_tpu/distributed/faults.py``
(kinds: drop_conn, delay, kill_after, refuse_accept; params n/p/times/
ms/for_s/side).  Stdlib only — runs on any host that can reach the
ports, no paddle_tpu import needed.  A worker that cannot be reached is
reported and skipped (its process may already be a casualty of the
scenario — that is not this tool's failure).
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.parse
import urllib.request


def _fetch(endpoint: str, query: str, timeout: float) -> dict:
    url = f"http://{endpoint}/chaosz" + (f"?{query}" if query else "")
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="inject/list/clear chaos faults on a live fleet "
                    "via the workers' debug servers")
    ap.add_argument("--endpoints", required=True,
                    help="comma-separated debug-server host:port list")
    ap.add_argument("--timeout", type=float, default=5.0)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_inject = sub.add_parser("inject", help="arm fault rules")
    p_inject.add_argument("spec", help="rule spec, e.g. "
                          "'drop_conn:send_vars:p=0.3;delay:get_task:ms=250'")
    sub.add_parser("list", help="show armed rules per worker")
    sub.add_parser("clear", help="remove runtime-injected rules")
    args = ap.parse_args(argv)

    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    query = ""
    if args.cmd == "inject":
        query = "inject=" + urllib.parse.quote(args.spec)
    elif args.cmd == "clear":
        query = "clear=1"

    rc = 0
    out = {}
    for ep in endpoints:
        try:
            out[ep] = _fetch(ep, query, args.timeout)
        except (urllib.error.URLError, OSError, ValueError) as e:
            out[ep] = {"unreachable": str(e)}
            rc = 1
    print(json.dumps(out, indent=2, sort_keys=True))
    return rc


if __name__ == "__main__":
    sys.exit(main())
