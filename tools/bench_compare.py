"""Bench regression gate: structured comparison of two BENCH rounds.

Compares two bench.py summary JSONs (raw summary lines, or the driver's
``BENCH_r*.json`` wrapper whose ``tail`` holds the summary as its last
JSON line) per config, with noise bands:

    python tools/bench_compare.py BENCH_r03.json BENCH_r06.json
    python tools/bench_compare.py old.json new.json --threshold 0.15
    python tools/bench_compare.py --find-baseline .   # newest measured round

Per config the HEADLINE metric (first of images/sec, tokens/sec,
samples/sec, tflops, ... present in BOTH rounds) is compared as a
relative delta.  Deltas beyond ``--threshold`` (default 10%, the
observed tunnel band) classify as regression/improvement; inside it,
within-noise.  Skip/error/analysis tags from the orchestrator are
honored: a config skipped in either round is reported but NEVER counted
as a regression, and analysis-only entries (``analysis: true`` —
cost-model numbers, not on-chip wall time) are compared informationally
but excluded from the verdict.  Exit code: 0 when no regression, 1 on
any regression beyond the band, 2 when a round cannot be loaded —
so CI and the bench orchestrator (which records the verdict in its
summary JSON) can gate on it.

Stdlib only — no paddle_tpu import needed.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional

# the frozen surface (tools/api_spec.txt): like cache_admin, the spec
# generator only sees functions listed here for non-package modules
__all__ = ["load_round", "measured_configs", "find_baseline", "compare",
           "render_text", "main"]

# headline throughput keys, in priority order; the first key present in
# BOTH rounds' config dicts is the compared metric (higher is better
# unless listed in LOWER_BETTER_KEYS)
METRIC_KEYS = (
    "images_per_sec",
    "tokens_per_sec",
    "samples_per_sec",
    "fused_samples_per_sec",
    "tflops",
    "implied_sp4_tokens_per_sec_per_device",
    "batched_storm_vars_per_sec",
    "batched_dense_mb_per_sec",
    "batched_qps",
    "decode_tokens_per_sec",
    "pipeline_samples_per_sec",
    "cold_vs_warm_speedup",
    "eff_flops",
    "pipeline_vs_link",
    "ckpt_overhead_frac",
    "recovery_mttr_s",
    "decode_ttft_ms_p99",
)

# cost-style headlines where SMALLER is the good direction (e.g. the
# async-snapshot step-loop overhead fraction): the delta sign flips for
# classification, the reported delta stays raw
LOWER_BETTER_KEYS = frozenset({"ckpt_overhead_frac", "recovery_mttr_s",
                               "decode_ttft_ms_p99", "canary_failures",
                               "kv_bytes_per_token",
                               "quant_accuracy_delta"})

# lower-better keys in ABSOLUTE units (seconds, not a fraction): their
# delta is relative when the baseline is positive — a 3 s -> 3.5 s MTTR
# drift is a 17% regression, while fraction keys (legitimately-0.0
# baselines) keep absolute-delta comparison
LOWER_BETTER_RELATIVE_KEYS = frozenset({"recovery_mttr_s",
                                        "decode_ttft_ms_p99"})

# tail-latency keys gated IN ADDITION to a config's headline: a round
# whose decode throughput held but whose TTFT p99 doubled must still
# read regression.  Each secondary present in BOTH rounds gets its own
# "<config>:<key>" entry with the same classification machinery.
# canary_failures rides the same gate: a round that got FASTER while
# the in-window golden canary started mismatching is a correctness
# regression, not a win.  prefix_hit_rate (higher-better, decode_prefix
# config) gates the same way: a dedup hit-rate collapse is a capacity
# regression even when the round's throughput happened to hold
SECONDARY_GATE_KEYS = ("decode_ttft_ms_p99", "canary_failures",
                       "prefix_hit_rate", "quant_accuracy_delta")

# informational keys carried through the comparison WITHOUT gating:
# recorded per config when present in either round (the evidence
# chain keeps capacity headroom + canary probe cost round-over-round),
# never classified, never part of the verdict
INFORMATIONAL_KEYS = ("headroom_frac", "canary_overhead_frac",
                      "kv_bytes_per_token", "unattributed_bytes")

DEFAULT_THRESHOLD = 0.10

# configs that are analysis-only BY NATURE (cost-model numbers): rounds
# older than the orchestrator's explicit ``analysis: true`` tagging
# carry them untagged, and an "all-skip except the cost model" round
# must not read as the last measured baseline
KNOWN_ANALYSIS_CONFIGS = frozenset({"scaling_dp8"})


def _is_analysis(name: str, cfg) -> bool:
    return bool(isinstance(cfg, dict) and cfg.get("analysis")) or \
        name in KNOWN_ANALYSIS_CONFIGS


def load_round(path: str) -> dict:
    """A bench summary dict from ``path``: either a raw summary JSON
    (has ``configs``) or the driver wrapper whose ``tail`` string holds
    the summary as its last parseable JSON line.  Raises ValueError
    when no summary is found (e.g. a timed-out round)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "configs" in doc:
        return doc
    tail = doc.get("tail", "") if isinstance(doc, dict) else ""
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict) and "configs" in cand:
            return cand
    raise ValueError(f"no bench summary (a 'configs' JSON) in {path}")


def _not_measured(cfg) -> Optional[str]:
    """Why a config record carries no measured number ('' = measured)."""
    if not isinstance(cfg, dict):
        return "malformed"
    if cfg.get("skipped"):
        return f"skipped: {cfg['skipped']}"
    if cfg.get("error"):
        return f"error: {cfg['error']}"
    return None


def _headline(old_cfg: dict, new_cfg: dict):
    for key in METRIC_KEYS:
        ov, nv = old_cfg.get(key), new_cfg.get(key)
        if isinstance(ov, (int, float)) and isinstance(nv, (int, float)):
            return key, float(ov), float(nv)
    return None, None, None


def measured_configs(summary: dict) -> List[str]:
    """Config names with a real on-chip measurement this round (not
    skipped/error/analysis, and carrying a headline metric)."""
    out = []
    for name, cfg in (summary.get("configs") or {}).items():
        if _not_measured(cfg) or not isinstance(cfg, dict) \
                or _is_analysis(name, cfg):
            continue
        if any(isinstance(cfg.get(k), (int, float)) for k in METRIC_KEYS):
            out.append(name)
    return sorted(out)


def find_baseline(dirname: str,
                  exclude: Optional[str] = None) -> Optional[str]:
    """Newest ``BENCH_r*.json`` under ``dirname`` that holds >= 1
    measured config — the last non-analysis round (an all-skip round
    like BENCH_r05 or a timed-out one like r04 is passed over)."""
    paths = sorted(glob.glob(os.path.join(dirname, "BENCH_r*.json")),
                   reverse=True)
    for path in paths:
        if exclude and os.path.abspath(path) == os.path.abspath(exclude):
            continue
        try:
            summary = load_round(path)
        except (OSError, ValueError):
            continue
        if measured_configs(summary):
            return path
    return None


def compare(old: dict, new: dict,
            threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Per-config delta classification of two summary dicts.

    Returns ``{"verdict", "threshold", "regressions", "improvements",
    "within_noise", "incomparable", "configs": {name: entry}}`` where
    each entry carries the compared metric, both values, the relative
    delta, and its classification.  Analysis-tagged configs compare
    informationally (``analysis: true``) and never drive the verdict.
    """
    old_cfgs = old.get("configs") or {}
    new_cfgs = new.get("configs") or {}
    out = {"threshold": threshold, "configs": {},
           "regressions": [], "improvements": [], "within_noise": [],
           "incomparable": []}
    for name in sorted(set(old_cfgs) | set(new_cfgs)):
        oc, nc = old_cfgs.get(name), new_cfgs.get(name)
        ent = {}
        why = None
        if oc is None:
            why = "new config (no baseline entry)"
        elif nc is None:
            why = "config absent from the new round"
        elif _not_measured(oc):
            why = f"baseline {_not_measured(oc)}"
        elif _not_measured(nc):
            why = f"new {_not_measured(nc)}"
        key = ov = nv = None
        if not why:
            key, ov, nv = _headline(oc, nc)
            if key is None:
                why = "no shared headline metric"
            elif (not ov or ov <= 0) and key not in LOWER_BETTER_KEYS:
                # a zero/negative baseline is a broken round, not a
                # clean within-noise verdict — surface, don't launder.
                # (Lower-better FRACTIONS compare by absolute delta, so
                # a 0.0 baseline there is legitimate — and excellent.)
                why = f"degenerate baseline value {key}={ov!r}"
        if why:
            ent["status"] = "incomparable"
            ent["reason"] = why
            out["incomparable"].append(name)
            out["configs"][name] = ent
            continue
        analysis = _is_analysis(name, oc) or _is_analysis(name, nc)
        _classify(out, name, ent, key, ov, nv, threshold, analysis)
        # informational carry-through: recorded, never classified
        for ikey in INFORMATIONAL_KEYS:
            iov, inv = oc.get(ikey), nc.get(ikey)
            if isinstance(iov, (int, float)) or \
                    isinstance(inv, (int, float)):
                ent.setdefault("info", {})[ikey] = {"old": iov,
                                                    "new": inv}
        # tail-latency secondaries gate NEXT TO the headline: a config
        # whose throughput held but whose TTFT p99 blew out must still
        # read regression (entries keyed "<config>:<metric>")
        for skey in SECONDARY_GATE_KEYS:
            if skey == key:
                continue
            sov, snv = oc.get(skey), nc.get(skey)
            if isinstance(sov, (int, float)) and \
                    isinstance(snv, (int, float)):
                _classify(out, f"{name}:{skey}", {}, skey,
                          float(sov), float(snv), threshold, analysis)
    out["verdict"] = "regression" if out["regressions"] else (
        "ok" if out["within_noise"] or out["improvements"] else "empty")
    return out


def _classify(out: dict, name: str, ent: dict, key: str,
              ov: float, nv: float, threshold: float,
              analysis: bool) -> None:
    """Delta + status for one (config, metric) pair, filed into the
    comparison dict (shared by headline and secondary-gate entries)."""
    if key in LOWER_BETTER_KEYS:
        # cost headline: sign flipped so "delta below -threshold"
        # still reads regression downstream; fractions compare by
        # absolute delta (0.0 baselines are legitimate), absolute-
        # unit keys (seconds/ms) relatively when the baseline allows
        if key in LOWER_BETTER_RELATIVE_KEYS and ov > 0:
            delta = -(nv - ov) / ov
        else:
            delta = -(nv - ov)
    else:
        delta = (nv - ov) / ov
    ent.update({"metric": key, "old": ov, "new": nv,
                "delta": round(delta, 4)})
    if key in LOWER_BETTER_KEYS:
        ent["lower_better"] = True
        ent["delta_abs"] = round(nv - ov, 4)
    if analysis:
        ent["analysis"] = True
    if delta < -threshold:
        ent["status"] = "regression"
    elif delta > threshold:
        ent["status"] = "improvement"
    else:
        ent["status"] = "within_noise"
    # analysis entries inform, never gate
    if analysis and ent["status"] == "regression":
        ent["status"] = "regression_analysis_only"
        out["within_noise"].append(name)
    else:
        out[{"regression": "regressions",
             "improvement": "improvements",
             "within_noise": "within_noise"}[ent["status"]]
            ].append(name)
    out["configs"][name] = ent


def render_text(cmp: dict) -> str:
    lines = [f"bench compare (threshold ±{cmp['threshold'] * 100:.0f}%): "
             f"verdict={cmp['verdict']}"]
    order = {"regression": 0, "regression_analysis_only": 1,
             "improvement": 2, "within_noise": 3, "incomparable": 4}
    items = sorted(cmp["configs"].items(),
                   key=lambda kv: (order.get(kv[1].get("status"), 9),
                                   kv[0]))
    for name, ent in items:
        if ent.get("status") == "incomparable":
            lines.append(f"  {name}: incomparable ({ent['reason']})")
            continue
        tag = " [analysis]" if ent.get("analysis") else ""
        lines.append(
            f"  {name}: {ent['status']}{tag}  {ent['metric']} "
            f"{ent['old']:g} -> {ent['new']:g} "
            f"({ent['delta'] * 100:+.1f}%)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare two bench rounds; exit 1 on regressions "
                    "beyond the noise band")
    ap.add_argument("old", nargs="?", help="baseline round JSON")
    ap.add_argument("new", nargs="?", help="new round JSON")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative noise band (default 0.10 = ±10%%)")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as JSON")
    ap.add_argument("--find-baseline", metavar="DIR",
                    help="print the newest measured BENCH_r*.json under "
                         "DIR and exit (what the orchestrator "
                         "auto-compares against)")
    args = ap.parse_args(argv)

    if args.find_baseline:
        path = find_baseline(args.find_baseline)
        if not path:
            print("no measured round found", file=sys.stderr)
            return 2
        print(path)
        return 0
    if not args.old or not args.new:
        ap.error("OLD and NEW round paths are required")
    try:
        old = load_round(args.old)
        new = load_round(args.new)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    cmp = compare(old, new, threshold=args.threshold)
    sys.stdout.write(json.dumps(cmp, indent=2) + "\n" if args.json
                     else render_text(cmp))
    return 1 if cmp["verdict"] == "regression" else 0


if __name__ == "__main__":
    sys.exit(main())
