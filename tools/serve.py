#!/usr/bin/env python
"""Stand up (or administer) a paddle_tpu model server from the CLI.

Serve a saved inference model dir (``fluid.io.save_inference_model``
output) on the framed-TCP serving endpoint, with continuous batching,
a warmed bucket ladder, and optional registry-announced replica
membership:

    python tools/serve.py /models/mnist/v1 --model mnist \\
        --endpoint 0.0.0.0:9000 --buckets 1,2,4,8,16,32 \\
        --max-delay-ms 5 --registry 10.0.0.2:8800 --debug-port 8080

    # serve a saved GENERATIVE model (decode.save_lm dir) with the
    # autoregressive decode plane: paged KV cache, token-level
    # continuous batching, streaming DECODE replies:
    python tools/serve.py /models/lm/v1 --model lm --decode \\
        --endpoint 0.0.0.0:9100 --decode-slots 8 --debug-port 8080

    # slots/cache/queue gauges of a running decode server:
    python tools/serve.py --decode --admin 10.0.0.7:9100 --status

    # hot-swap a new version into a RUNNING server (zero downtime):
    python tools/serve.py /models/mnist/v2 --model mnist --version 2 \\
        --admin 10.0.0.7:9000 --swap

    # router + batching gauges of a running server:
    python tools/serve.py --admin 10.0.0.7:9000 --status

With ``FLAGS_compile_cache_dir`` set, the bucket-ladder warm pool
hydrates from the persistent compile cache — a server restart or a
swap on a previously-seen version pays zero XLA compiles
(``executor.persistent_hits``).  ``--debug-port`` exposes /servingz
(and the rest of the observability plane) over HTTP.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

__all__ = ["build_parser", "main"]

# runnable as `python tools/serve.py` from anywhere: the repo root
# (paddle_tpu's parent) must be importable
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="serve.py",
        description="paddle_tpu model server / serving admin CLI")
    p.add_argument("model_dir", nargs="?", default=None,
                   help="saved inference model dir (save_inference_model)")
    p.add_argument("--model", default="default",
                   help="served model name (default: %(default)s)")
    p.add_argument("--version", default="1",
                   help="model version label (default: %(default)s)")
    p.add_argument("--endpoint", default="127.0.0.1:0",
                   help="host:port to serve on (default ephemeral loopback)")
    p.add_argument("--registry", default=None, metavar="HOST:PORT",
                   help="announce this replica via the pserver registry")
    p.add_argument("--replica-id", default=None,
                   help="replica id in the registry key (default: endpoint)")
    p.add_argument("--buckets", default=None,
                   help="batch-size ladder, e.g. 1,2,4,8,16,32 "
                        "(default: FLAGS_serving_buckets)")
    p.add_argument("--max-delay-ms", type=float, default=None,
                   help="max queue delay before a partial batch dispatches")
    p.add_argument("--max-queue-rows", type=int, default=None,
                   help="admission-control queue bound in rows")
    p.add_argument("--slo-ms", type=float, default=None,
                   help="queue-delay SLO: shed when it is unmeetable")
    p.add_argument("--max-seq-len", type=int, default=None,
                   help="per-model sequence-length bound: an over-length "
                        "request is rejected at submit with a typed "
                        "RequestTooLong instead of poisoning its batch")
    # decode mode ----------------------------------------------------------
    p.add_argument("--decode", action="store_true",
                   help="serve model_dir as a GENERATIVE model "
                        "(decode.save_lm layout) on the streaming decode "
                        "plane instead of one-shot inference")
    p.add_argument("--decode-slots", type=int, default=None,
                   help="decode-batch width (default: "
                        "FLAGS_decode_max_slots)")
    p.add_argument("--decode-block-tokens", type=int, default=None,
                   help="paged KV cache block size in tokens (default: "
                        "FLAGS_decode_block_tokens)")
    p.add_argument("--decode-prefill-buckets", default=None,
                   help="prompt-length ladder, e.g. 16,32,64,128 "
                        "(default: FLAGS_decode_prefill_buckets)")
    p.add_argument("--no-warm", action="store_true",
                   help="skip the bucket-ladder warm pool (first requests "
                        "pay the compiles)")
    p.add_argument("--no-ir-optim", action="store_true",
                   help="disable the analysis fusion passes")
    p.add_argument("--debug-port", type=int, default=0,
                   help="debug HTTP server port (/servingz etc.); 0 = off")
    # admin mode -----------------------------------------------------------
    p.add_argument("--admin", default=None, metavar="HOST:PORT",
                   help="administer a RUNNING server instead of serving")
    p.add_argument("--status", action="store_true",
                   help="with --admin: print the server's router + gauges")
    p.add_argument("--swap", action="store_true",
                   help="with --admin: hot-swap model_dir in as "
                        "--model @ --version")
    return p


def _bucket_list(spec):
    if spec is None:
        return None
    from paddle_tpu.serving import BucketLadder
    return BucketLadder.parse(spec)


def _batcher_kw(args) -> dict:
    kw = {}
    if args.max_delay_ms is not None:
        kw["max_delay_ms"] = args.max_delay_ms
    if args.max_queue_rows is not None:
        kw["max_queue_rows"] = args.max_queue_rows
    if args.slo_ms is not None:
        kw["queue_delay_slo_ms"] = args.slo_ms
    if args.max_seq_len is not None:
        kw["max_seq_len"] = args.max_seq_len
    return kw


def _serve_decode(args) -> int:
    """Stand up a streaming decode server for a saved LM dir."""
    import paddle_tpu as fluid  # noqa: F401 (registers lowerings)
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.decode import DecodeEngine, DecodeServer, load_lm
    from paddle_tpu.serving import BucketLadder

    if args.debug_port:
        _flags.set_flags({"debug_server_port": args.debug_port})
    lm, params = load_lm(args.model_dir)
    kw = {}
    if args.decode_slots is not None:
        kw["max_slots"] = args.decode_slots
    if args.decode_block_tokens is not None:
        kw["block_tokens"] = args.decode_block_tokens
    if args.decode_prefill_buckets is not None:
        kw["prefill_buckets"] = BucketLadder.parse(
            args.decode_prefill_buckets)
    eng = DecodeEngine(lm, params, name=args.model, **kw)
    srv = DecodeServer(args.endpoint, engines={args.model: eng},
                       registry_ep=args.registry,
                       replica_id=args.replica_id)
    srv.start()
    print(json.dumps({
        "decoding": f"{args.model}@{args.version}",
        "endpoint": srv.endpoint,
        "model": lm.config.to_dict(),
        "max_slots": eng.max_slots,
        "block_tokens": eng.cache.block_tokens,
        "prefill_buckets": list(eng.prefill_ladder.sizes),
        "registry": args.registry,
        "debug_port": args.debug_port or None}, default=repr), flush=True)

    stop = threading.Event()
    drain = {"requested": False}

    def on_signal(signum, frame):
        # SIGTERM = graceful drain: deregister the lease first, let
        # in-flight streams generate to their FIN, reject stragglers
        # with a typed Draining — zero dropped streams on a rolling
        # restart.  SIGINT stays immediate.
        drain["requested"] = signum == signal.SIGTERM
        stop.set()
    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop.wait(1.0):
            pass
    finally:
        srv.stop(drain=drain["requested"])
        print("decode server stopped"
              + (" (drained)" if drain["requested"] else ""), flush=True)
    return 0


def _admin(args) -> int:
    from paddle_tpu.serving import ServingClient

    if args.decode:
        from paddle_tpu.decode import DecodeClient
        out = DecodeClient(endpoints=[args.admin]).status(args.admin)
        print(json.dumps(out, indent=2, default=repr))
        return 0

    cli = ServingClient(endpoints=[args.admin])
    if args.swap:
        if not args.model_dir:
            print("--swap needs a model_dir", file=sys.stderr)
            return 2
        cmd = {"cmd": "swap", "model": args.model,
               "version": args.version, "model_dir": args.model_dir}
        buckets = _bucket_list(args.buckets)
        if buckets:
            cmd["buckets"] = buckets
        cmd.update(_batcher_kw(args))
        out = cli.admin(args.admin, cmd)
    else:  # default: status
        out = cli.admin(args.admin, {"cmd": "status"})
    print(json.dumps(out, indent=2, default=repr))
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.admin:
        return _admin(args)
    if not args.model_dir:
        print("model_dir is required (or use --admin)", file=sys.stderr)
        return 2
    if args.decode:
        return _serve_decode(args)

    import paddle_tpu as fluid  # noqa: F401 (registers lowerings)
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.inference.predictor import AnalysisConfig
    from paddle_tpu.serving import ModelServer

    if args.debug_port:
        _flags.set_flags({"debug_server_port": args.debug_port})
    cfg = AnalysisConfig(args.model_dir)
    if args.no_ir_optim:
        cfg.switch_ir_optim(False)
    srv = ModelServer(args.endpoint, registry_ep=args.registry,
                      replica_id=args.replica_id)
    srv.load(args.model, args.version, model_dir=args.model_dir,
             config=cfg, warm=not args.no_warm,
             buckets=_bucket_list(args.buckets), activate=True,
             **_batcher_kw(args))
    srv.start()
    sm = srv.manager.models()[0]
    print(json.dumps({
        "serving": f"{args.model}@{args.version}",
        "endpoint": srv.endpoint,
        "buckets": list(sm.batcher.ladder.sizes),
        "warm": sm.warm_info,
        "registry": args.registry,
        "debug_port": args.debug_port or None}, default=repr), flush=True)

    stop = threading.Event()
    drain = {"requested": False}

    def on_signal(signum, frame):
        # SIGTERM = graceful drain (the supervisor/orchestrator
        # shutdown path): deregister first, finish in-flight, then
        # close — zero dropped requests.  SIGINT stays immediate.
        drain["requested"] = signum == signal.SIGTERM
        stop.set()
    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop.wait(1.0):
            pass
    finally:
        srv.stop(drain=drain["requested"])
        print("server stopped"
              + (" (drained)" if drain["requested"] else ""), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
