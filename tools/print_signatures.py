"""Dump the public API surface of paddle_tpu as stable one-line records.

Reference role: ``tools/print_signatures.py`` (clean-room — same gate
capability, fresh implementation): every public function/class signature
prints as ``<qualified name> (args..., defaults...)`` so a checked-in
spec (``tools/api_spec.txt``) can freeze the surface and
``tools/diff_api.py`` / ``tests/test_api_freeze.py`` can fail CI on
accidental drift.

Usage: python tools/print_signatures.py [> tools/api_spec.txt]
"""
from __future__ import annotations

import importlib
import inspect
import sys


MODULES = [
    "paddle_tpu",
    "paddle_tpu.layers",
    "paddle_tpu.layers.learning_rate_scheduler",
    "paddle_tpu.layers.detection",
    "paddle_tpu.layers.metric_op",
    "paddle_tpu.nets",
    "paddle_tpu.optimizer",
    "paddle_tpu.initializer",
    "paddle_tpu.regularizer",
    "paddle_tpu.clip",
    "paddle_tpu.metrics",
    "paddle_tpu.io",
    "paddle_tpu.profiler",
    "paddle_tpu.observability",
    "paddle_tpu.observability.stats",
    "paddle_tpu.observability.step_stats",
    "paddle_tpu.observability.debug_server",
    "paddle_tpu.observability.health",
    "paddle_tpu.observability.aggregate",
    # the distributed-tracing + flight-recorder surface (trace ids,
    # sampling, span ring, stitching, crash dumps): frozen so wire/API
    # drift in the trace layer is loud
    "paddle_tpu.observability.trace",
    "paddle_tpu.observability.flight",
    # the perf/numerics attribution plane (cost/memory records,
    # rooflines, device-memory sampling, run-scalar log) + its operator
    # CLIs: frozen so record/log-format drift is loud
    "paddle_tpu.observability.perf",
    "paddle_tpu.observability.runlog",
    # the latency-anatomy / SLO plane (phase timelines, metric history
    # rings, SLO watchdog): frozen so the rule grammar, ring wire form
    # and phase-record shape drift loudly
    "paddle_tpu.observability.phase",
    "paddle_tpu.observability.history",
    "paddle_tpu.observability.slo",
    # the saturation-anatomy plane (phase utilization + capacity
    # modeling, per-tenant metering): frozen so the snapshot shapes
    # and the STATS_PULL rider forms drift loudly
    "paddle_tpu.observability.capacity",
    "paddle_tpu.observability.tenant",
    # the correctness plane (golden canary prober, divergence audit
    # ring) + the golden-set operator CLI: frozen so the golden file
    # format, digest scheme and rider shapes drift loudly
    "paddle_tpu.observability.canary",
    "paddle_tpu.observability.audit",
    # the memory-attribution plane (per-pool HBM ledger, event ring,
    # leak sentinel, OOM forensics): frozen so the ledger/rider shapes
    # and the /allocz payload drift loudly
    "paddle_tpu.observability.memory",
    "golden",          # tools/golden.py (tools/ on sys.path here)
    "bench_compare",   # tools/bench_compare.py (tools/ on sys.path here)
    "runlog_report",   # tools/runlog_report.py
    # pipeline parallelism plane (stage transpiler, schedules, drivers,
    # permute transport, RPC stage workers): frozen so the stage-program
    # contract and schedule API drift loudly
    "paddle_tpu.pipeline",
    "paddle_tpu.pipeline.transpiler",
    "paddle_tpu.pipeline.schedule",
    "paddle_tpu.pipeline.runner",
    "paddle_tpu.pipeline.permute",
    "paddle_tpu.pipeline.rpc",
    # autoregressive decode plane (paged KV cache, continuous decode
    # batching, streaming server/client): frozen so the generative
    # serving API + wire tags drift loudly
    "paddle_tpu.decode",
    "paddle_tpu.decode.cache",
    "paddle_tpu.decode.model",
    "paddle_tpu.decode.engine",
    "paddle_tpu.decode.server",
    "paddle_tpu.decode.client",
    "paddle_tpu.lod_tensor",
    "paddle_tpu.transpiler",
    "paddle_tpu.data_feeder",
    "paddle_tpu.param_attr",
    "paddle_tpu.average",
    "paddle_tpu.evaluator",
    "paddle_tpu.net_drawer",
    "paddle_tpu.debugger",
    "paddle_tpu.recordio_writer",
    # distributed/parallel/inference surfaces (VERDICT r4 #6): these
    # public classes churn the most — freeze them too
    "paddle_tpu.distributed",
    # the var-transport wire surface (batched SEND_VARS/GET_VARS,
    # scatter-gather serde): frozen so wire-format/API drift is loud
    "paddle_tpu.distributed.serde",
    "paddle_tpu.distributed.transport",
    # the HA control plane (standby registration/promotion/REG_SNAPSHOT,
    # replicated pserver loop, leader-elected master, fault-injection
    # rule grammar) + its operator CLI: frozen so failover/wire drift
    # is loud
    "paddle_tpu.distributed.registry",
    "paddle_tpu.distributed.master",
    "paddle_tpu.distributed.faults",
    # the self-healing fleet supervisor (FleetSpec grammar, worker
    # lifecycle state machine, rollback/resize actions) + its operator
    # CLI: frozen so the spec-file format and admin surface drift
    # loudly
    "paddle_tpu.distributed.supervisor",
    "fleet",        # tools/fleet.py (tools/ is on sys.path here)
    "chaos",        # tools/chaos.py (tools/ is on sys.path here)
    "paddle_tpu.parallel",
    "paddle_tpu.inference",
    # the model-serving plane (bucket-ladder batching, hot-swap model
    # registry, INFER wire, replica client) + its operator CLI: frozen
    # so the serving wire/API surface drifts loudly
    "paddle_tpu.serving",
    "paddle_tpu.serving.batcher",
    "paddle_tpu.serving.model_registry",
    "paddle_tpu.serving.server",
    "paddle_tpu.serving.client",
    "serve",        # tools/serve.py (tools/ is on sys.path here)
    "paddle_tpu.contrib.trainer",
    "paddle_tpu.contrib.inferencer",
    "paddle_tpu.contrib.decoder",
    # the persistent compile-cache surface (entry format, fingerprint,
    # store/load/prune) + its operator CLI: frozen so on-disk format /
    # admin-tooling drift is loud
    "paddle_tpu.core.compile_cache",
    "cache_admin",  # tools/cache_admin.py (tools/ is on sys.path here)
    # the fused sparse-embedding kernel surface (FLAGS_sparse_fused_kernel
    # gather/update entry points + the lowering peephole planner): frozen
    # so the optimizer-wiring contract drifts loudly
    "paddle_tpu.kernels.sparse",
    # the low-precision serving surface (fused-dequant int8 matmul,
    # calibration plan, KV qdq helpers, /quantz payload): frozen so the
    # scale semantics and fallback contract drift loudly
    "paddle_tpu.kernels.quant",
    # the sharded-checkpoint plane (manifest/store/reshard/snapshot/
    # elastic) + its operator CLI: frozen so the on-disk format and the
    # restore-planner contract drift loudly
    "paddle_tpu.checkpoint",
    "paddle_tpu.checkpoint.manifest",
    "paddle_tpu.checkpoint.store",
    "paddle_tpu.checkpoint.reshard",
    "paddle_tpu.checkpoint.snapshot",
    "paddle_tpu.checkpoint.elastic",
    "ckpt_admin",   # tools/ckpt_admin.py (tools/ on sys.path here)
]


def _sig(obj) -> str:
    try:
        sig = inspect.signature(obj)
    except (TypeError, ValueError):
        return "(signature unavailable)"
    parts = []
    for p in sig.parameters.values():
        if p.default is inspect.Parameter.empty:
            parts.append(p.name)
        else:
            parts.append(f"{p.name}={p.default!r}")
    return "(" + ", ".join(parts) + ")"


def iter_api():
    for modname in MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        declared = getattr(mod, "__all__", None)
        names = declared if declared is not None else \
            [n for n in dir(mod) if not n.startswith("_")]
        for name in sorted(names):
            obj = getattr(mod, name, None)
            if obj is None:
                continue
            if inspect.ismodule(obj):
                continue
            if declared is None:
                # dir() fallback only: skip re-exports (typing etc.) —
                # an explicit __all__ may deliberately re-export
                own = getattr(obj, "__module__", modname) or modname
                if not own.startswith("paddle_tpu"):
                    continue
            if inspect.isclass(obj):
                yield f"{modname}.{name}.__init__ {_sig(obj.__init__)}"
                for m_name, m in sorted(vars(obj).items()):
                    if m_name.startswith("_"):
                        continue
                    if callable(m):
                        yield f"{modname}.{name}.{m_name} {_sig(m)}"
            elif callable(obj):
                yield f"{modname}.{name} {_sig(obj)}"


def main():
    for line in sorted(set(iter_api())):
        print(line)


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
