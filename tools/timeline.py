#!/usr/bin/env python
"""Convert profiler span dumps to a Chrome trace file (reference
tools/timeline.py:115).

The TPU profiler (`paddle_tpu/profiler.py`) already emits Chrome-trace
JSON natively, so this tool is a thin CLI over it: merge one or more
span-dump files (the `profiler.stop_profiler(dump_path)` output) into a
single chrome://tracing-loadable file, offsetting pids per input like the
reference merges multi-device profiles.

Usage: python tools/timeline.py --profile_path a.json,b.json \
       --timeline_path timeline.json
"""
from __future__ import annotations

import argparse
import json


def merge(paths):
    events = []
    for pid, path in enumerate(paths):
        with open(path) as f:
            data = json.load(f)
        evs = data if isinstance(data, list) else data.get("traceEvents", [])
        for e in evs:
            e = dict(e)
            # third-party traces (XLA dumps, hand-written markers) may
            # omit tid/pid; catapult requires both, so default tid to 0
            # instead of raising (pid is re-homed per input file anyway)
            e.setdefault("tid", 0)
            e["pid"] = pid
            events.append(e)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"profile {path}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--profile_path", required=True,
                   help="comma-separated span-dump json files")
    p.add_argument("--timeline_path", required=True)
    args = p.parse_args(argv)
    out = merge([s for s in args.profile_path.split(",") if s])
    with open(args.timeline_path, "w") as f:
        json.dump(out, f)
    print(f"wrote {args.timeline_path}")


if __name__ == "__main__":
    main()
