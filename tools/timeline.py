#!/usr/bin/env python
"""Convert profiler span dumps to a Chrome trace file (reference
tools/timeline.py:115).

The TPU profiler (`paddle_tpu/profiler.py`) already emits Chrome-trace
JSON natively, so this tool is a thin CLI over it: merge one or more
span-dump files (the `profiler.stop_profiler(dump_path)` output) into a
single chrome://tracing-loadable file, offsetting pids per input like the
reference merges multi-device profiles.

Usage: python tools/timeline.py --profile_path a.json,b.json \
       --timeline_path timeline.json
"""
from __future__ import annotations

import argparse
import json


def _alloc_pid(used, want):
    pid = want
    while pid in used:
        pid += 1
    used.add(pid)
    return pid


def merge(paths):
    """Merge trace files into one multi-process timeline.

    Files that already carry ``pid``s — per-process profiler dumps and
    the stitched multi-process JSON from ``tools/stitch_trace.py`` —
    keep them (a cross-file collision bumps the later file's pid, same
    relative layout), so real process identities and their
    ``process_name`` metadata survive the merge.  Events without a pid
    (third-party traces, hand markers) are homed per input file, with
    tid defaulted to 0 (catapult requires both)."""
    events = []
    used = set()
    for idx, path in enumerate(paths):
        with open(path) as f:
            data = json.load(f)
        evs = data if isinstance(data, list) else data.get("traceEvents", [])
        own_pids = sorted({e["pid"] for e in evs if "pid" in e})
        pid_map = {p: _alloc_pid(used, p) for p in own_pids}
        default_pid = None
        has_meta = any(e.get("ph") == "M" and e.get("name") == "process_name"
                       for e in evs)
        for e in evs:
            e = dict(e)
            e.setdefault("tid", 0)
            if "pid" in e:
                e["pid"] = pid_map[e["pid"]]
            else:
                if default_pid is None:
                    default_pid = _alloc_pid(used, idx)
                e["pid"] = default_pid
            events.append(e)
        if not has_meta:
            for pid in (pid_map.values() if pid_map
                        else ([default_pid] if default_pid is not None
                              else [])):
                events.append({"name": "process_name", "ph": "M", "pid": pid,
                               "args": {"name": f"profile {path}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--profile_path", required=True,
                   help="comma-separated span-dump json files")
    p.add_argument("--timeline_path", required=True)
    args = p.parse_args(argv)
    out = merge([s for s in args.profile_path.split(",") if s])
    with open(args.timeline_path, "w") as f:
        json.dump(out, f)
    print(f"wrote {args.timeline_path}")


if __name__ == "__main__":
    main()
