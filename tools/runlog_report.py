"""Render / compare run-scalar logs (observability/runlog.py JSONL).

Operator companion to ``FLAGS_run_log_dir``: every ``Executor.run`` /
``run_steps`` appends one JSON object per step (scalar fetches by name,
grad global norm, step_ms, samples/sec).  This tool turns those files
into something a human or a dashboard ingests:

    python tools/runlog_report.py run_1234.jsonl             # text summary
    python tools/runlog_report.py run_1234.jsonl --csv       # CSV to stdout
    python tools/runlog_report.py a.jsonl --compare b.jsonl  # two-run diff
    python tools/runlog_report.py run_1234.jsonl --json      # summary JSON

The summary reports, per scalar series: first/last/min/max/mean and a
non-finite count (a NaN'd loss is loud even without the executor's
numerics sentinel armed).  ``--compare`` lines up two runs by step
index and reports final-value deltas per shared scalar plus step-time
and throughput ratios — the "did my change speed it up or break
convergence" question in one command.

Stdlib only — runs anywhere the log files are readable, no paddle_tpu
import needed.  Exit code: 0 on success, 2 when a log cannot be read
or holds no records.
"""
from __future__ import annotations

import argparse
import csv
import json
import math
import sys
from typing import Dict, List, Optional

# the frozen surface (tools/api_spec.txt): like cache_admin, the spec
# generator only sees functions listed here for non-package modules
__all__ = ["load", "summarize", "render_text", "write_csv", "compare",
           "render_compare", "main"]


def load(path: str) -> List[dict]:
    """Parse one JSONL run log; torn/blank lines are skipped (a live
    writer may be racing us at a rotation boundary)."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _series_stats(vals: List[float]) -> dict:
    finite = [v for v in vals if isinstance(v, (int, float))
              and math.isfinite(v)]
    out = {
        "n": len(vals),
        "nonfinite": len(vals) - len(finite),
        "first": vals[0] if vals else None,
        "last": vals[-1] if vals else None,
    }
    if finite:
        out["min"] = min(finite)
        out["max"] = max(finite)
        out["mean"] = sum(finite) / len(finite)
    return out


def summarize(records: List[dict]) -> dict:
    """Aggregate one run: step span, wall span, step-time / throughput
    means, per-scalar series stats, grad-norm series stats."""
    steps = [r.get("step") for r in records if r.get("step") is not None]
    tss = [r.get("ts") for r in records if isinstance(r.get("ts"),
                                                     (int, float))]
    scalars: Dict[str, List[float]] = {}
    for r in records:
        for name, v in (r.get("scalars") or {}).items():
            scalars.setdefault(name, []).append(v)
    out = {
        "records": len(records),
        "step_first": min(steps) if steps else None,
        "step_last": max(steps) if steps else None,
        "wall_span_s": round(max(tss) - min(tss), 3) if len(tss) > 1 else 0.0,
        "step_ms": _series_stats(
            [r["step_ms"] for r in records if "step_ms" in r]),
        "samples_per_sec": _series_stats(
            [r["samples_per_sec"] for r in records
             if "samples_per_sec" in r]),
        "grad_global_norm": _series_stats(
            [r["grad_global_norm"] for r in records
             if "grad_global_norm" in r]),
        "scalars": {name: _series_stats(vals)
                    for name, vals in sorted(scalars.items())},
    }
    return out


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_text(summary: dict, label: str = "") -> str:
    lines = [f"run log{' ' + label if label else ''}: "
             f"{summary['records']} records, steps "
             f"{_fmt(summary['step_first'])}..{_fmt(summary['step_last'])}, "
             f"{_fmt(summary['wall_span_s'])} s wall"]
    for key in ("step_ms", "samples_per_sec", "grad_global_norm"):
        st = summary[key]
        if st["n"]:
            lines.append(
                f"  {key}: mean={_fmt(st.get('mean'))} "
                f"min={_fmt(st.get('min'))} max={_fmt(st.get('max'))} "
                f"last={_fmt(st['last'])}")
    for name, st in summary["scalars"].items():
        nf = f"  NONFINITE={st['nonfinite']}" if st["nonfinite"] else ""
        lines.append(
            f"  scalar {name}: first={_fmt(st['first'])} "
            f"last={_fmt(st['last'])} min={_fmt(st.get('min'))} "
            f"max={_fmt(st.get('max'))}{nf}")
    return "\n".join(lines) + "\n"


def write_csv(records: List[dict], fh) -> None:
    """Flat CSV: fixed columns + one column per scalar name seen."""
    names = sorted({n for r in records
                    for n in (r.get("scalars") or {})})
    w = csv.writer(fh)
    w.writerow(["step", "ts", "step_ms", "samples_per_sec",
                "grad_global_norm"] + names)
    for r in records:
        sc = r.get("scalars") or {}
        w.writerow([r.get("step"), r.get("ts"), r.get("step_ms"),
                    r.get("samples_per_sec"), r.get("grad_global_norm")]
                   + [sc.get(n) for n in names])


def compare(a: List[dict], b: List[dict]) -> dict:
    """Two-run diff: final-value delta per shared scalar + step-time /
    throughput ratios (b relative to a)."""
    sa, sb = summarize(a), summarize(b)
    out = {"a": {"records": sa["records"]}, "b": {"records": sb["records"]},
           "scalars": {}}
    for name in sorted(set(sa["scalars"]) & set(sb["scalars"])):
        fa = sa["scalars"][name]["last"]
        fb = sb["scalars"][name]["last"]
        ent = {"a_last": fa, "b_last": fb}
        if isinstance(fa, (int, float)) and isinstance(fb, (int, float)) \
                and math.isfinite(fa) and math.isfinite(fb):
            ent["delta"] = fb - fa
        out["scalars"][name] = ent
    for key in ("step_ms", "samples_per_sec"):
        ma = sa[key].get("mean")
        mb = sb[key].get("mean")
        if ma and mb:
            out[key + "_ratio"] = round(mb / ma, 4)
    return out


def render_compare(cmp: dict) -> str:
    lines = [f"compare: a={cmp['a']['records']} records, "
             f"b={cmp['b']['records']} records"]
    for key in ("step_ms_ratio", "samples_per_sec_ratio"):
        if key in cmp:
            lines.append(f"  {key.replace('_ratio', '')} b/a: {cmp[key]}")
    for name, ent in cmp["scalars"].items():
        delta = f" delta={_fmt(ent['delta'])}" if "delta" in ent else ""
        lines.append(f"  scalar {name}: a_last={_fmt(ent['a_last'])} "
                     f"b_last={_fmt(ent['b_last'])}{delta}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render / compare run-scalar JSONL logs "
                    "(FLAGS_run_log_dir)")
    ap.add_argument("log", help="run log JSONL path")
    ap.add_argument("--compare", metavar="OTHER",
                    help="second log: report final-value deltas and "
                         "step-time/throughput ratios (OTHER vs LOG)")
    ap.add_argument("--csv", action="store_true",
                    help="emit the records as CSV instead of a summary")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary (or comparison) as JSON")
    args = ap.parse_args(argv)

    try:
        records = load(args.log)
    except OSError as e:
        print(f"cannot read {args.log}: {e}", file=sys.stderr)
        return 2
    if not records:
        print(f"no records in {args.log}", file=sys.stderr)
        return 2

    if args.csv:
        write_csv(records, sys.stdout)
        return 0
    if args.compare:
        try:
            other = load(args.compare)
        except OSError as e:
            print(f"cannot read {args.compare}: {e}", file=sys.stderr)
            return 2
        if not other:
            print(f"no records in {args.compare}", file=sys.stderr)
            return 2
        cmp = compare(records, other)
        sys.stdout.write(json.dumps(cmp, indent=2) + "\n" if args.json
                         else render_compare(cmp))
        return 0
    summary = summarize(records)
    sys.stdout.write(json.dumps(summary, indent=2) + "\n" if args.json
                     else render_text(summary, label=args.log))
    return 0


if __name__ == "__main__":
    sys.exit(main())
