"""Record / inspect / replay golden canary sets (observability/canary.py).

The golden canary prober replays recorded input -> expected-output
pairs through live replicas; this tool captures those pairs against a
TRUSTED build — record on a build you believe, then every later build
is continuously regression-checked against it in production:

    # feeds.json: {"cases": [{"feeds": {"x": {"dtype": "float32",
    #                                         "shape": [1, 4],
    #                                         "data": [..flat..]}}}]}
    python tools/golden.py record --model mnist --feeds feeds.json \
        --endpoint 127.0.0.1:9000 --out golden.json --rtol 1e-5
    python tools/golden.py show golden.json
    python tools/golden.py replay golden.json --model mnist \
        --endpoint 127.0.0.1:9000     # offline parity check

``record`` sends each feeds case through the real INFER path
(``ServingClient.infer_pairs``, tenant-tagged ``__canary__``) and
stores the replies as the expected outputs.  ``replay`` re-sends and
compares with the set's rtol — the same comparison the in-process
prober runs, usable as a one-shot parity check between two builds.
``--registry`` records through registry discovery instead of a static
endpoint.

Trust caveat (module doc of canary.py): a golden set blesses whatever
build recorded it.  Keep provenance honest — the recorded endpoint,
time, and case count are stamped into the file.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.observability import canary as _canary  # noqa: E402


def load_feeds(path: str) -> List[Dict[str, object]]:
    """Parse a feeds file into a list of decoded feed dicts."""
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    cases = payload["cases"] if isinstance(payload, dict) else payload
    out = []
    for case in cases:
        enc = case.get("feeds") if isinstance(case, dict) else case
        out.append({n: _canary.decode_array(e) for n, e in enc.items()})
    return out


def record_cases(infer_pairs_fn: Callable, model: str,
                 feeds_list: List[dict], rtol: Optional[float] = None,
                 provenance: Optional[dict] = None) -> "_canary.GoldenSet":
    """Build a :class:`GoldenSet` by running every feeds case through
    ``infer_pairs_fn(feeds) -> [(name, array), ...]`` (the trusted
    build).  Library entry point — the CLI wraps a ServingClient
    around it, tests pass a local predictor closure."""
    cases = []
    for feeds in feeds_list:
        expect = [(str(n), v) for n, v in infer_pairs_fn(feeds)]
        cases.append({"feeds": dict(feeds), "expect": expect})
    gs = _canary.GoldenSet()
    gs.provenance = dict(provenance or {})
    gs.models[str(model)] = {"rtol": rtol, "cases": cases}
    return gs


def write_goldens(gs: "_canary.GoldenSet", path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(gs.to_payload(), f, indent=2, sort_keys=True)
        f.write("\n")


def replay_cases(infer_pairs_fn: Callable, gs: "_canary.GoldenSet",
                 model: str) -> List[Optional[str]]:
    """Replay one model's goldens; returns per-case ``None`` (pass) or
    the mismatch description (the prober's own comparison)."""
    rtol = gs.rtol(model)
    results = []
    for case in gs.cases(model):
        got = infer_pairs_fn(case["feeds"])
        results.append(_canary.compare_pairs(case["expect"], got, rtol))
    return results


def _make_client(args):
    from paddle_tpu.serving.client import ServingClient
    if args.registry:
        return ServingClient(registry_ep=args.registry)
    if args.endpoint:
        return ServingClient(endpoints=[args.endpoint])
    raise SystemExit("need --endpoint or --registry")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="record / inspect / replay golden canary sets")
    sub = ap.add_subparsers(dest="cmd", required=True)

    rec = sub.add_parser("record", help="capture goldens from a "
                         "trusted live build")
    rec.add_argument("--model", required=True)
    rec.add_argument("--feeds", required=True,
                     help="feeds JSON ({'cases': [{'feeds': ...}]})")
    rec.add_argument("--out", required=True, help="golden JSON to write")
    rec.add_argument("--rtol", type=float, default=None,
                     help="per-model rtol stored in the set (default: "
                     "prober falls back to FLAGS_canary_rtol)")
    rec.add_argument("--endpoint", help="static serving replica")
    rec.add_argument("--registry", help="discover replicas by registry")

    shw = sub.add_parser("show", help="summarize a golden set")
    shw.add_argument("path")

    rep = sub.add_parser("replay", help="replay goldens against a live "
                         "build and compare")
    rep.add_argument("path")
    rep.add_argument("--model", required=True)
    rep.add_argument("--endpoint", help="static serving replica")
    rep.add_argument("--registry", help="discover replicas by registry")

    args = ap.parse_args(argv)

    if args.cmd == "show":
        gs = _canary.load_goldens(args.path)
        print(json.dumps({
            "provenance": gs.provenance,
            "models": {m: {"rtol": spec.get("rtol"),
                           "cases": len(spec["cases"])}
                       for m, spec in gs.models.items()}}, indent=2,
            sort_keys=True))
        return 0

    if args.cmd == "record":
        client = _make_client(args)
        feeds_list = load_feeds(args.feeds)
        gs = record_cases(
            lambda feeds: client.infer_pairs(
                args.model, feeds, tenant=_canary.CANARY_TENANT),
            args.model, feeds_list, rtol=args.rtol,
            provenance={"recorded_unix_s": int(time.time()),
                        "endpoint": args.endpoint or args.registry,
                        "cases": len(feeds_list)})
        write_goldens(gs, args.out)
        print(f"recorded {len(feeds_list)} case(s) for model "
              f"{args.model!r} -> {args.out}")
        return 0

    # replay
    gs = _canary.load_goldens(args.path)
    client = _make_client(args)
    results = replay_cases(
        lambda feeds: client.infer_pairs(
            args.model, feeds, tenant=_canary.CANARY_TENANT),
        gs, args.model)
    fails = [(i, r) for i, r in enumerate(results) if r is not None]
    for i, r in fails:
        print(f"FAIL case {i}: {r}")
    print(f"{len(results) - len(fails)}/{len(results)} case(s) passed")
    return 1 if fails else 0


if __name__ == "__main__":
    raise SystemExit(main())
