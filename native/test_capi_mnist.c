/* Pure-C mnist inference smoke test for the paddle_tpu C API.
 *
 * Mirrors the reference's native-deployment demos
 * (paddle/legacy/capi/examples/model_inference/dense/main.c role;
 * fluid/train/test_train_recognize_digits.cc for the "drive the saved
 * model without writing Python" capability).  This file uses ONLY
 * paddle_tpu_capi.h + libc — no Python API anywhere.
 *
 * Usage: test_capi_mnist <saved_inference_model_dir>
 * Exit 0 when: predictor loads, a [B,1,28,28] batch runs, the output is
 * [B,10] probabilities summing to ~1 per row.
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "paddle_tpu_capi.h"

#define B 8

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <model_dir>\n", argv[0]);
    return 2;
  }
  pt_predictor* pred = pt_predictor_create(argv[1]);
  if (pred == NULL) {
    fprintf(stderr, "create failed: %s\n", pt_last_error());
    return 1;
  }
  int n_in = pt_predictor_num_inputs(pred);
  int n_out = pt_predictor_num_outputs(pred);
  printf("predictor: %d inputs, %d outputs\n", n_in, n_out);
  if (n_in != 1 || n_out < 1) {
    fprintf(stderr, "unexpected io arity\n");
    return 1;
  }
  const char* in_name = pt_predictor_input_name(pred, 0);
  printf("feed name: %s\n", in_name);

  static float pixels[B * 1 * 28 * 28];
  unsigned seed = 7;
  for (size_t i = 0; i < sizeof(pixels) / sizeof(float); ++i) {
    seed = seed * 1664525u + 1013904223u;
    pixels[i] = ((float)(seed >> 8) / (float)(1 << 24)) - 0.5f;
  }

  pt_tensor in;
  memset(&in, 0, sizeof(in));
  in.name = in_name;
  in.dtype = PT_FLOAT32;
  in.ndim = 4;
  in.shape[0] = B; in.shape[1] = 1; in.shape[2] = 28; in.shape[3] = 28;
  in.data = pixels;
  in.nbytes = sizeof(pixels);

  pt_tensor out[4];
  int wrote = pt_predictor_run(pred, &in, 1, out, n_out > 4 ? 4 : n_out);
  if (wrote < 1) {
    fprintf(stderr, "run failed: %s\n", pt_last_error());
    return 1;
  }
  if (out[0].dtype != PT_FLOAT32 || out[0].ndim != 2 ||
      out[0].shape[0] != B || out[0].shape[1] != 10) {
    fprintf(stderr, "bad output shape: ndim=%d [%lld,%lld] dtype=%d\n",
            out[0].ndim, (long long)out[0].shape[0],
            (long long)out[0].shape[1], (int)out[0].dtype);
    return 1;
  }
  const float* probs = (const float*)out[0].data;
  for (int b = 0; b < B; ++b) {
    float s = 0.f;
    for (int c = 0; c < 10; ++c) s += probs[b * 10 + c];
    if (fabsf(s - 1.0f) > 1e-3f) {
      fprintf(stderr, "row %d probs sum %.5f != 1\n", b, s);
      return 1;
    }
  }

  /* clone-per-thread contract: a clone must produce identical results */
  pt_predictor* clone = pt_predictor_clone(pred);
  if (clone == NULL) {
    fprintf(stderr, "clone failed: %s\n", pt_last_error());
    return 1;
  }
  pt_tensor out2[4];
  if (pt_predictor_run(clone, &in, 1, out2, 1) < 1) {
    fprintf(stderr, "clone run failed: %s\n", pt_last_error());
    return 1;
  }
  if (memcmp(out[0].data, out2[0].data, out[0].nbytes) != 0) {
    fprintf(stderr, "clone output differs\n");
    return 1;
  }

  for (int i = 0; i < wrote; ++i) pt_tensor_free(&out[i]);
  pt_tensor_free(&out2[0]);
  pt_predictor_destroy(clone);
  pt_predictor_destroy(pred);
  printf("OK: mnist inference via C API, %d batches of %d, probs valid\n",
         2, B);
  return 0;
}
