// C inference API implementation: embeds CPython and drives the
// paddle_tpu Predictor through paddle_tpu/inference/capi_bridge.py.
// See paddle_tpu_capi.h for the contract and reference citations
// (legacy/capi/capi.h; inference/api/paddle_inference_api.h:141,211 —
// clean-room reimplementation of the deployment CAPABILITY, not the code).
//
// Marshaling is bytes-only (PyBytes/PyLong/PyUnicode): no numpy headers,
// no ctypes — Python.h is the only dependency beyond libc.
#include "paddle_tpu_capi.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

namespace {

thread_local std::string g_err;
thread_local std::string g_name;  // borrowed-string storage for name lookups

void set_err(const char* where) {
  g_err = where;
  if (PyErr_Occurred()) {
    PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
    PyErr_Fetch(&type, &value, &tb);
    PyErr_NormalizeException(&type, &value, &tb);
    if (value != nullptr) {
      PyObject* s = PyObject_Str(value);
      if (s != nullptr) {
        const char* msg = PyUnicode_AsUTF8(s);  // may fail -> NULL
        if (msg != nullptr) {
          g_err += ": ";
          g_err += msg;
        }
        Py_DECREF(s);
      }
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
  }
}

const char* dtype_name(pt_dtype d) {
  switch (d) {
    case PT_FLOAT32:  return "float32";
    case PT_INT64:    return "int64";
    case PT_INT32:    return "int32";
    case PT_FLOAT64:  return "float64";
    case PT_UINT8:    return "uint8";
    case PT_BFLOAT16: return "bfloat16";
  }
  return "float32";
}

int dtype_from_name(const char* n, pt_dtype* out) {
  if (std::strcmp(n, "float32") == 0) { *out = PT_FLOAT32; return 0; }
  if (std::strcmp(n, "int64") == 0)   { *out = PT_INT64;   return 0; }
  if (std::strcmp(n, "int32") == 0)   { *out = PT_INT32;   return 0; }
  if (std::strcmp(n, "float64") == 0) { *out = PT_FLOAT64; return 0; }
  if (std::strcmp(n, "uint8") == 0)   { *out = PT_UINT8;   return 0; }
  if (std::strcmp(n, "bfloat16") == 0) { *out = PT_BFLOAT16; return 0; }
  return -1;
}

PyObject* g_bridge = nullptr;        // paddle_tpu.inference.capi_bridge
PyObject* g_train_bridge = nullptr;  // paddle_tpu.train.capi_bridge

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

// Build the bridge wire list [(name, dtype, shape, bytes), ...] from
// borrowed input tensors.  Returns NULL with g_err set on failure.
// Caller holds the GIL.
PyObject* marshal_inputs(const char* where, const pt_tensor* inputs,
                         int n_in) {
  PyObject* ins = PyList_New(n_in);
  if (ins == nullptr) {
    PyErr_Clear();
    g_err = std::string(where) + ": input list alloc";
    return nullptr;
  }
  for (int i = 0; i < n_in; ++i) {
    const pt_tensor& t = inputs[i];
    if (t.ndim < 0 || t.ndim > 8) {
      Py_DECREF(ins);
      g_err = std::string(where) + ": input ndim out of range [0, 8]";
      return nullptr;
    }
    PyObject* shape = PyTuple_New(t.ndim);
    if (shape == nullptr) {
      Py_DECREF(ins);
      PyErr_Clear();
      g_err = std::string(where) + ": input shape alloc";
      return nullptr;
    }
    for (int d = 0; d < t.ndim; ++d) {
      PyObject* dim = PyLong_FromLongLong(t.shape[d]);
      if (dim == nullptr) {
        Py_DECREF(shape);
        Py_DECREF(ins);
        PyErr_Clear();
        g_err = std::string(where) + ": input dim alloc";
        return nullptr;
      }
      PyTuple_SET_ITEM(shape, d, dim);
    }
    PyObject* tup = Py_BuildValue(
        "(ssOy#)", t.name, dtype_name(t.dtype), shape,
        static_cast<const char*>(t.data), (Py_ssize_t)t.nbytes);
    Py_DECREF(shape);
    if (tup == nullptr) {
      Py_DECREF(ins);
      g_err = std::string(where) + ": input marshal";
      PyErr_Clear();
      return nullptr;
    }
    PyList_SET_ITEM(ins, i, tup);
  }
  return ins;
}

// Fill one owned output tensor from a bridge (dtype, shape, bytes)
// tuple.  Returns 0, or -1 with g_err set (no buffer left allocated).
// Caller holds the GIL.
int fill_output(const char* where, PyObject* tup, pt_tensor* o) {
  std::memset(o, 0, sizeof(*o));
  // a bridge bug (or a user-monkeypatched bridge) must surface as a
  // -1 + g_err, never as a segfault of the embedding process: validate
  // the whole (dtype, shape, bytes) tuple shape before touching items
  if (tup == nullptr || !PyTuple_Check(tup) || PyTuple_Size(tup) < 3) {
    PyErr_Clear();
    g_err = std::string(where) +
            ": output is not a (dtype, shape, bytes) tuple";
    return -1;
  }
  const char* dt = PyUnicode_AsUTF8(PyTuple_GetItem(tup, 0));
  if (dt == nullptr) {
    PyErr_Clear();
    g_err = std::string(where) + ": output dtype marshal";
    return -1;
  }
  if (dtype_from_name(dt, &o->dtype) != 0) {
    g_err = std::string(where) + ": unsupported output dtype " + dt;
    return -1;
  }
  PyObject* shape = PyTuple_GetItem(tup, 1);
  if (shape == nullptr || !PyTuple_Check(shape)) {
    PyErr_Clear();
    g_err = std::string(where) + ": output shape is not a tuple";
    return -1;
  }
  int ndim = static_cast<int>(PyTuple_Size(shape));
  if (ndim > 8) {
    g_err = std::string(where) + ": output rank > 8 unsupported";
    return -1;
  }
  o->ndim = ndim;
  for (int d = 0; d < ndim; ++d) {
    o->shape[d] = PyLong_AsLongLong(PyTuple_GetItem(shape, d));
    if (o->shape[d] == -1 && PyErr_Occurred()) {
      PyErr_Clear();
      g_err = std::string(where) + ": output shape dim is not an int";
      return -1;
    }
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(PyTuple_GetItem(tup, 2), &buf, &len) != 0) {
    PyErr_Clear();
    g_err = std::string(where) + ": output bytes marshal";
    return -1;
  }
  o->nbytes = static_cast<size_t>(len);
  o->data = std::malloc(o->nbytes ? o->nbytes : 1);
  if (o->data == nullptr) {
    o->nbytes = 0;
    g_err = std::string(where) + ": out of memory";
    return -1;
  }
  std::memcpy(o->data, buf, o->nbytes);
  o->name = nullptr;
  return 0;
}

}  // namespace

struct pt_predictor {
  long handle;
};

struct pt_trainer {
  long handle;
};

extern "C" {

int pt_init(void) {
  // initialization itself must be serialized (two threads racing
  // Py_InitializeEx is undefined behavior); steady-state calls only
  // take the GIL
  static std::mutex init_mu;
  std::lock_guard<std::mutex> lk(init_mu);
  if (g_bridge != nullptr) return 0;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Py_InitializeEx leaves the GIL held by THIS thread; release it so
    // every capi call (from any thread, including this one) goes through
    // the Gil ensure/release pair — otherwise worker threads running the
    // clone-per-thread contract deadlock while this thread sits in C.
    PyEval_SaveThread();
  }
  Gil gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
  if (mod == nullptr) {
    set_err("pt_init: import paddle_tpu.inference.capi_bridge failed "
            "(is paddle_tpu on PYTHONPATH?)");
    return -1;
  }
  g_bridge = mod;  // keep the reference for process lifetime
  return 0;
}

pt_predictor* pt_predictor_create(const char* model_dir) {
  if (pt_init() != 0) return nullptr;
  Gil gil;
  PyObject* h = PyObject_CallMethod(g_bridge, "create", "s", model_dir);
  if (h == nullptr) {
    set_err("pt_predictor_create");
    return nullptr;
  }
  long handle = PyLong_AsLong(h);
  Py_DECREF(h);
  pt_predictor* p = new pt_predictor{handle};
  return p;
}

pt_predictor* pt_predictor_clone(pt_predictor* p) {
  Gil gil;
  PyObject* h = PyObject_CallMethod(g_bridge, "clone", "l", p->handle);
  if (h == nullptr) {
    set_err("pt_predictor_clone");
    return nullptr;
  }
  pt_predictor* c = new pt_predictor{PyLong_AsLong(h)};
  Py_DECREF(h);
  return c;
}

int pt_predictor_num_inputs(pt_predictor* p) {
  Gil gil;
  PyObject* names = PyObject_CallMethod(g_bridge, "feed_names", "l",
                                        p->handle);
  if (names == nullptr) { set_err("pt_predictor_num_inputs"); return -1; }
  int n = static_cast<int>(PyList_Size(names));
  Py_DECREF(names);
  return n;
}

const char* pt_predictor_input_name(pt_predictor* p, int i) {
  Gil gil;
  PyObject* names = PyObject_CallMethod(g_bridge, "feed_names", "l",
                                        p->handle);
  if (names == nullptr || i < 0 || i >= PyList_Size(names)) {
    Py_XDECREF(names);
    set_err("pt_predictor_input_name: index out of range");
    return nullptr;
  }
  // borrowed via thread-local storage (valid until next name lookup)
  const char* nm = PyUnicode_AsUTF8(PyList_GetItem(names, i));
  if (nm == nullptr) {
    Py_DECREF(names);
    set_err("pt_predictor_input_name: non-utf8 name");
    return nullptr;
  }
  g_name = nm;
  Py_DECREF(names);
  return g_name.c_str();
}

int pt_predictor_num_outputs(pt_predictor* p) {
  Gil gil;
  PyObject* n = PyObject_CallMethod(g_bridge, "fetch_count", "l", p->handle);
  if (n == nullptr) { set_err("pt_predictor_num_outputs"); return -1; }
  int v = static_cast<int>(PyLong_AsLong(n));
  Py_DECREF(n);
  return v;
}

int pt_predictor_run(pt_predictor* p, const pt_tensor* inputs, int n_in,
                     pt_tensor* outputs, int n_out) {
  Gil gil;
  PyObject* ins = marshal_inputs("pt_predictor_run", inputs, n_in);
  if (ins == nullptr) return -1;
  PyObject* outs = PyObject_CallMethod(g_bridge, "run", "lO",
                                       p->handle, ins);
  Py_DECREF(ins);
  if (outs == nullptr) {
    set_err("pt_predictor_run");
    return -1;
  }
  int n = static_cast<int>(PyList_Size(outs));
  int written = 0;
  for (int i = 0; i < n && i < n_out; ++i) {
    if (fill_output("pt_predictor_run", PyList_GetItem(outs, i),
                    &outputs[i]) != 0) {
      // the caller cannot know how many slots were written — free them
      for (int j = 0; j < written; ++j) pt_tensor_free(&outputs[j]);
      Py_DECREF(outs);
      return -1;
    }
    ++written;
  }
  Py_DECREF(outs);
  return written;
}

void pt_tensor_free(pt_tensor* t) {
  if (t != nullptr && t->data != nullptr) {
    std::free(t->data);
    t->data = nullptr;
    t->nbytes = 0;
  }
}

void pt_predictor_destroy(pt_predictor* p) {
  if (p == nullptr) return;
  if (g_bridge != nullptr && Py_IsInitialized()) {
    Gil gil;
    PyObject* r = PyObject_CallMethod(g_bridge, "destroy", "l", p->handle);
    Py_XDECREF(r);
    PyErr_Clear();
  }
  delete p;
}

/* ------------------------- trainer surface ------------------------- */

static int pt_train_init(void) {
  if (pt_init() != 0) return -1;  // interpreter + shared machinery
  if (g_train_bridge != nullptr) return 0;
  Gil gil;
  if (g_train_bridge == nullptr) {
    PyObject* mod = PyImport_ImportModule("paddle_tpu.train.capi_bridge");
    if (mod == nullptr) {
      set_err("pt_trainer: import paddle_tpu.train.capi_bridge failed");
      return -1;
    }
    g_train_bridge = mod;  // process-lifetime reference
  }
  return 0;
}

pt_trainer* pt_trainer_create(const char* model_dir) {
  if (pt_train_init() != 0) return nullptr;
  Gil gil;
  PyObject* h = PyObject_CallMethod(g_train_bridge, "create", "s",
                                    model_dir);
  if (h == nullptr) {
    set_err("pt_trainer_create");
    return nullptr;
  }
  pt_trainer* t = new pt_trainer{PyLong_AsLong(h)};
  Py_DECREF(h);
  return t;
}

int pt_trainer_num_inputs(pt_trainer* t) {
  Gil gil;
  PyObject* names = PyObject_CallMethod(g_train_bridge, "feed_names", "l",
                                        t->handle);
  if (names == nullptr) { set_err("pt_trainer_num_inputs"); return -1; }
  int n = static_cast<int>(PyList_Size(names));
  Py_DECREF(names);
  return n;
}

const char* pt_trainer_input_name(pt_trainer* t, int i) {
  Gil gil;
  PyObject* names = PyObject_CallMethod(g_train_bridge, "feed_names", "l",
                                        t->handle);
  if (names == nullptr || i < 0 || i >= PyList_Size(names)) {
    Py_XDECREF(names);
    set_err("pt_trainer_input_name: index out of range");
    return nullptr;
  }
  const char* nm = PyUnicode_AsUTF8(PyList_GetItem(names, i));
  if (nm == nullptr) {
    Py_DECREF(names);
    set_err("pt_trainer_input_name: non-utf8 name");
    return nullptr;
  }
  g_name = nm;
  Py_DECREF(names);
  return g_name.c_str();
}

int pt_trainer_step(pt_trainer* t, const pt_tensor* inputs, int n_in,
                    pt_tensor* loss_out) {
  Gil gil;
  PyObject* ins = marshal_inputs("pt_trainer_step", inputs, n_in);
  if (ins == nullptr) return -1;
  PyObject* tup = PyObject_CallMethod(g_train_bridge, "step", "lO",
                                      t->handle, ins);
  Py_DECREF(ins);
  if (tup == nullptr) {
    set_err("pt_trainer_step");
    return -1;
  }
  int rc = fill_output("pt_trainer_step", tup, loss_out);
  Py_DECREF(tup);
  return rc;
}

int pt_trainer_save(pt_trainer* t, const char* dirname) {
  Gil gil;
  PyObject* r = PyObject_CallMethod(g_train_bridge, "save", "ls",
                                    t->handle, dirname);
  if (r == nullptr) {
    set_err("pt_trainer_save");
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

void pt_trainer_destroy(pt_trainer* t) {
  if (t == nullptr) return;
  if (g_train_bridge != nullptr && Py_IsInitialized()) {
    Gil gil;
    PyObject* r = PyObject_CallMethod(g_train_bridge, "destroy", "l",
                                      t->handle);
    Py_XDECREF(r);
    PyErr_Clear();
  }
  delete t;
}

const char* pt_last_error(void) { return g_err.c_str(); }

}  // extern "C"
