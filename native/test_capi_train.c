/* Pure-C training smoke: load a fluid.io.save_train_model directory,
 * run 20 optimizer steps on a fixed synthetic batch, assert the loss
 * decreases, and write a checkpoint — no Python authored by the caller.
 * Reference capability: paddle/fluid/train/demo/demo_trainer.cc (loads
 * saved ProgramDescs, loops executor.Run, reads the loss tensor).
 *
 * Usage: test_capi_train <model_dir> <save_dir>
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "paddle_tpu_capi.h"

#define BATCH 16
#define STEPS 20

/* deterministic pseudo-random floats in [-1, 1] (no libc rand state) */
static unsigned int lcg_state = 12345u;
static float lcg_unit(void) {
  lcg_state = lcg_state * 1664525u + 1013904223u;
  return ((float)(lcg_state >> 8) / (float)(1u << 24)) * 2.0f - 1.0f;
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <save_dir>\n", argv[0]);
    return 2;
  }

  pt_trainer* t = pt_trainer_create(argv[1]);
  if (t == NULL) {
    fprintf(stderr, "create failed: %s\n", pt_last_error());
    return 1;
  }

  int n_in = pt_trainer_num_inputs(t);
  if (n_in != 2) {
    fprintf(stderr, "expected 2 feeds, got %d (%s)\n", n_in,
            pt_last_error());
    return 1;
  }
  /* input_name returns a borrowed per-thread buffer valid until the
   * next lookup — print each name before fetching the next */
  printf("feed 0: %s\n", pt_trainer_input_name(t, 0));
  printf("feed 1: %s\n", pt_trainer_input_name(t, 1));

  /* one fixed batch, repeated every step: the loss on it must drop */
  static float pixels[BATCH * 1 * 28 * 28];
  static int64_t labels[BATCH];
  for (int i = 0; i < BATCH * 28 * 28; ++i) pixels[i] = lcg_unit();
  for (int i = 0; i < BATCH; ++i) labels[i] = i % 10;

  pt_tensor in[2];
  memset(in, 0, sizeof(in));
  in[0].name = "pixel";
  in[0].dtype = PT_FLOAT32;
  in[0].ndim = 4;
  in[0].shape[0] = BATCH; in[0].shape[1] = 1;
  in[0].shape[2] = 28;    in[0].shape[3] = 28;
  in[0].data = pixels;
  in[0].nbytes = sizeof(pixels);
  in[1].name = "label";
  in[1].dtype = PT_INT64;
  in[1].ndim = 2;
  in[1].shape[0] = BATCH; in[1].shape[1] = 1;
  in[1].data = labels;
  in[1].nbytes = sizeof(labels);

  float first = 0.0f, last = 0.0f;
  for (int step = 0; step < STEPS; ++step) {
    pt_tensor loss;
    if (pt_trainer_step(t, in, 2, &loss) != 0) {
      fprintf(stderr, "step %d failed: %s\n", step, pt_last_error());
      return 1;
    }
    if (loss.dtype != PT_FLOAT32 || loss.nbytes < sizeof(float)) {
      fprintf(stderr, "unexpected loss tensor (dtype %d, %zu bytes)\n",
              (int)loss.dtype, loss.nbytes);
      return 1;
    }
    float v = ((float*)loss.data)[0];
    pt_tensor_free(&loss);
    if (step == 0) first = v;
    last = v;
    if (step % 5 == 0 || step == STEPS - 1) {
      printf("step %d loss %f\n", step, (double)v);
    }
  }

  if (!(last < first)) {
    fprintf(stderr, "loss did not decrease: first=%f last=%f\n",
            (double)first, (double)last);
    return 1;
  }

  if (pt_trainer_save(t, argv[2]) != 0) {
    fprintf(stderr, "save failed: %s\n", pt_last_error());
    return 1;
  }
  pt_trainer_destroy(t);

  printf("OK: mnist train via C API, loss %f -> %f\n", (double)first,
         (double)last);
  return 0;
}
