/* paddle_tpu C inference API — native deployment without writing Python.
 *
 * Reference roles mirrored (clean-room, semantics only):
 *   - paddle/legacy/capi/capi.h:1            (pure-C deployment surface)
 *   - paddle/fluid/inference/api/paddle_inference_api.h:141,211
 *     (PaddlePredictor::Run / CreatePaddlePredictor contract)
 *
 * The implementation (paddle_tpu_capi.cc) embeds CPython and drives the
 * paddle_tpu Predictor; the CALLER never touches Python — this header is
 * plain C and links like any C library:
 *
 *   cc app.c -lpaddle_tpu_capi -o app
 *
 * Threading: one pt_predictor per thread (mirror of the reference
 * clone-per-thread contract) — create clones with pt_predictor_clone.
 * All calls are serialized internally on the embedded interpreter's GIL.
 */
#ifndef PADDLE_TPU_CAPI_H
#define PADDLE_TPU_CAPI_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  PT_FLOAT32 = 0,
  PT_INT64 = 1,
  PT_INT32 = 2,
  PT_FLOAT64 = 3,
  PT_UINT8 = 4,
  PT_BFLOAT16 = 5,
} pt_dtype;

/* Borrowed-view tensor for inputs; owned-buffer tensor for outputs
 * (free output tensors with pt_tensor_free). */
typedef struct {
  const char* name;     /* feed name; ignored for outputs            */
  pt_dtype dtype;
  int ndim;
  int64_t shape[8];
  void* data;           /* row-major contiguous                      */
  size_t nbytes;
} pt_tensor;

typedef struct pt_predictor pt_predictor;

/* Initialize the embedded runtime (idempotent; called lazily by
 * pt_predictor_create too).  Returns 0 on success. */
int pt_init(void);

/* Load a saved inference model directory (fluid.io.save_inference_model
 * layout) and build a predictor.  NULL on failure — see pt_last_error. */
pt_predictor* pt_predictor_create(const char* model_dir);

/* Same weights, private executable cache — one clone per serving thread. */
pt_predictor* pt_predictor_clone(pt_predictor* p);

/* Run one batch.  inputs: n_in borrowed tensors (data not copied until
 * the call).  outputs: caller-provided array of n_out slots, filled with
 * malloc'd buffers in the model's fetch order.  Returns the number of
 * outputs written, or -1 on error. */
int pt_predictor_run(pt_predictor* p, const pt_tensor* inputs, int n_in,
                     pt_tensor* outputs, int n_out);

/* Number of feeds / fetches; feed name by index (borrowed string). */
int pt_predictor_num_inputs(pt_predictor* p);
int pt_predictor_num_outputs(pt_predictor* p);
const char* pt_predictor_input_name(pt_predictor* p, int i);

void pt_tensor_free(pt_tensor* t);
void pt_predictor_destroy(pt_predictor* p);

/* Last error message for this thread (borrowed; valid until next call). */
const char* pt_last_error(void);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H */
