/* paddle_tpu C inference API — native deployment without writing Python.
 *
 * Reference roles mirrored (clean-room, semantics only):
 *   - paddle/legacy/capi/capi.h:1            (pure-C deployment surface)
 *   - paddle/fluid/inference/api/paddle_inference_api.h:141,211
 *     (PaddlePredictor::Run / CreatePaddlePredictor contract)
 *
 * The implementation (paddle_tpu_capi.cc) embeds CPython and drives the
 * paddle_tpu Predictor; the CALLER never touches Python — this header is
 * plain C and links like any C library:
 *
 *   cc app.c -lpaddle_tpu_capi -o app
 *
 * Threading: one pt_predictor per thread (mirror of the reference
 * clone-per-thread contract) — create clones with pt_predictor_clone.
 * All calls are serialized internally on the embedded interpreter's GIL.
 */
#ifndef PADDLE_TPU_CAPI_H
#define PADDLE_TPU_CAPI_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  PT_FLOAT32 = 0,
  PT_INT64 = 1,
  PT_INT32 = 2,
  PT_FLOAT64 = 3,
  PT_UINT8 = 4,
  PT_BFLOAT16 = 5,
} pt_dtype;

/* Borrowed-view tensor for inputs; owned-buffer tensor for outputs
 * (free output tensors with pt_tensor_free). */
typedef struct {
  const char* name;     /* feed name; ignored for outputs            */
  pt_dtype dtype;
  int ndim;
  int64_t shape[8];
  void* data;           /* row-major contiguous                      */
  size_t nbytes;
} pt_tensor;

typedef struct pt_predictor pt_predictor;

/* Initialize the embedded runtime (idempotent; called lazily by
 * pt_predictor_create too).  Returns 0 on success. */
int pt_init(void);

/* Load a saved inference model directory (fluid.io.save_inference_model
 * layout) and build a predictor.  NULL on failure — see pt_last_error. */
pt_predictor* pt_predictor_create(const char* model_dir);

/* Same weights, private executable cache — one clone per serving thread. */
pt_predictor* pt_predictor_clone(pt_predictor* p);

/* Run one batch.  inputs: n_in borrowed tensors (data not copied until
 * the call).  outputs: caller-provided array of n_out slots, filled with
 * malloc'd buffers in the model's fetch order.  Returns the number of
 * outputs written, or -1 on error. */
int pt_predictor_run(pt_predictor* p, const pt_tensor* inputs, int n_in,
                     pt_tensor* outputs, int n_out);

/* Number of feeds / fetches; feed name by index (borrowed string). */
int pt_predictor_num_inputs(pt_predictor* p);
int pt_predictor_num_outputs(pt_predictor* p);
const char* pt_predictor_input_name(pt_predictor* p, int i);

void pt_tensor_free(pt_tensor* t);
void pt_predictor_destroy(pt_predictor* p);

/* ------------------------------------------------------------------ *
 * Native TRAINING (reference role: paddle/fluid/train/demo/
 * demo_trainer.cc — load a saved train program, run steps, read loss).
 * Model directories come from fluid.io.save_train_model (full main +
 * startup programs + persistable state); pt_trainer_save writes the
 * same layout, so checkpoints round-trip between C and Python.
 * ------------------------------------------------------------------ */
typedef struct pt_trainer pt_trainer;

/* Load a save_train_model directory.  NULL on failure (pt_last_error). */
pt_trainer* pt_trainer_create(const char* model_dir);

/* Feed introspection (same contract as the predictor's). */
int pt_trainer_num_inputs(pt_trainer* t);
const char* pt_trainer_input_name(pt_trainer* t, int i);

/* Run ONE optimizer step on a batch.  inputs: n_in borrowed tensors.
 * loss_out: filled with a malloc'd scalar/vector loss tensor (free with
 * pt_tensor_free).  Returns 0 on success, -1 on error. */
int pt_trainer_step(pt_trainer* t, const pt_tensor* inputs, int n_in,
                    pt_tensor* loss_out);

/* Checkpoint: save programs + all persistable state (params, optimizer
 * moments, LR counters) into dirname.  Returns 0 on success. */
int pt_trainer_save(pt_trainer* t, const char* dirname);

void pt_trainer_destroy(pt_trainer* t);

/* Last error message for this thread (borrowed; valid until next call). */
const char* pt_last_error(void);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H */
