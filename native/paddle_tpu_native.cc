// Native runtime components for paddle_tpu (C ABI, loaded via ctypes).
//
// TPU-native equivalents of the reference's native data-path pieces:
//  - BlockingQueue: bounded MPMC byte-buffer queue feeding the device input
//    pipeline (reference: operators/reader/lod_tensor_blocking_queue.h and
//    the double-buffer reader's staging queue).
//  - RecordIO: chunked record file format with per-chunk CRC32 and optional
//    zlib compression (reference: paddle/fluid/recordio/{header,chunk,
//    scanner,writer} — same structure: magic, per-chunk record count,
//    compressor tag, checksum).
//  - ThreadPool: fixed worker pool used by the host-side pipeline
//    (reference: framework/threadpool.h).
//
// Build: make -C native   (g++ -O2 -fPIC -shared -lz -lpthread)

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <zlib.h>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>
#include <cerrno>
#include <chrono>

extern "C" {

// ---------------------------------------------------------------------------
// BlockingQueue of byte buffers
// ---------------------------------------------------------------------------

struct Queue {
  size_t capacity;
  std::deque<std::string> items;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  bool closed = false;
};

void* ptq_queue_create(size_t capacity) {
  auto* q = new Queue();
  q->capacity = capacity ? capacity : 1;
  return q;
}

// blocks while full; returns 0 on success, -1 if closed
int ptq_queue_push(void* qp, const char* data, size_t len) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_full.wait(lk, [q] { return q->items.size() < q->capacity || q->closed; });
  if (q->closed) return -1;
  q->items.emplace_back(data, len);
  q->not_empty.notify_one();
  return 0;
}

// blocks while empty; returns length (malloc'd into *out), -1 if closed+drained
long ptq_queue_pop(void* qp, char** out) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [q] { return !q->items.empty() || q->closed; });
  if (q->items.empty()) return -1;
  std::string s = std::move(q->items.front());
  q->items.pop_front();
  q->not_full.notify_one();
  lk.unlock();
  *out = static_cast<char*>(malloc(s.size()));
  memcpy(*out, s.data(), s.size());
  return static_cast<long>(s.size());
}

void ptq_buffer_free(char* buf) { free(buf); }

void ptq_queue_close(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

size_t ptq_queue_size(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

int ptq_queue_closed(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->closed ? 1 : 0;
}

void ptq_queue_destroy(void* qp) { delete static_cast<Queue*>(qp); }

// ---------------------------------------------------------------------------
// RecordIO (recordio/header.h:25 layout concept: chunked, CRC, compressor)
// ---------------------------------------------------------------------------

static const uint32_t kMagic = 0x50545152;  // "PTQR"
enum Compressor { kNone = 0, kZlib = 1 };

struct ChunkHeader {
  uint32_t magic;
  uint32_t num_records;
  uint32_t compressor;
  uint32_t crc32;
  uint64_t payload_len;  // on-disk (possibly compressed) length
};

struct Writer {
  FILE* f;
  int compressor;
  size_t max_records;
  std::string buf;       // raw concatenated (len,data) records
  uint32_t num_records = 0;
};

static int write_chunk(Writer* w) {
  if (w->num_records == 0) return 0;
  std::string payload;
  if (w->compressor == kZlib) {
    uLongf dst_len = compressBound(w->buf.size());
    payload.resize(dst_len);
    if (compress2(reinterpret_cast<Bytef*>(&payload[0]), &dst_len,
                  reinterpret_cast<const Bytef*>(w->buf.data()),
                  w->buf.size(), Z_DEFAULT_COMPRESSION) != Z_OK)
      return -1;
    payload.resize(dst_len);
  } else {
    payload = w->buf;
  }
  ChunkHeader h;
  h.magic = kMagic;
  h.num_records = w->num_records;
  h.compressor = static_cast<uint32_t>(w->compressor);
  h.crc32 = static_cast<uint32_t>(
      crc32(0L, reinterpret_cast<const Bytef*>(payload.data()), payload.size()));
  h.payload_len = payload.size();
  // raw length follows header so the scanner can size its buffer
  uint64_t raw_len = w->buf.size();
  if (fwrite(&h, sizeof(h), 1, w->f) != 1) return -1;
  if (fwrite(&raw_len, sizeof(raw_len), 1, w->f) != 1) return -1;
  if (!payload.empty() && fwrite(payload.data(), payload.size(), 1, w->f) != 1)
    return -1;
  w->buf.clear();
  w->num_records = 0;
  return 0;
}

void* ptq_recordio_writer_open(const char* path, int compressor,
                               size_t max_chunk_records) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  w->compressor = compressor;
  w->max_records = max_chunk_records ? max_chunk_records : 1000;
  return w;
}

int ptq_recordio_write(void* wp, const char* data, size_t len) {
  auto* w = static_cast<Writer*>(wp);
  uint32_t l = static_cast<uint32_t>(len);
  w->buf.append(reinterpret_cast<const char*>(&l), sizeof(l));
  w->buf.append(data, len);
  w->num_records++;
  if (w->num_records >= w->max_records) return write_chunk(w);
  return 0;
}

int ptq_recordio_writer_close(void* wp) {
  auto* w = static_cast<Writer*>(wp);
  int rc = write_chunk(w);
  fclose(w->f);
  delete w;
  return rc;
}

struct Scanner {
  FILE* f;
  std::string chunk;          // decompressed current chunk
  size_t offset = 0;
  uint32_t remaining = 0;
};

void* ptq_recordio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new Scanner();
  s->f = f;
  return s;
}

static int load_chunk(Scanner* s) {
  ChunkHeader h;
  if (fread(&h, sizeof(h), 1, s->f) != 1) return -1;  // EOF
  if (h.magic != kMagic) return -2;
  uint64_t raw_len;
  if (fread(&raw_len, sizeof(raw_len), 1, s->f) != 1) return -2;
  std::string payload(h.payload_len, '\0');
  if (h.payload_len &&
      fread(&payload[0], h.payload_len, 1, s->f) != 1)
    return -2;
  uint32_t crc = static_cast<uint32_t>(crc32(
      0L, reinterpret_cast<const Bytef*>(payload.data()), payload.size()));
  if (crc != h.crc32) return -3;  // corruption detected
  if (h.compressor == kZlib) {
    s->chunk.resize(raw_len);
    uLongf dst = raw_len;
    if (uncompress(reinterpret_cast<Bytef*>(&s->chunk[0]), &dst,
                   reinterpret_cast<const Bytef*>(payload.data()),
                   payload.size()) != Z_OK)
      return -2;
  } else {
    s->chunk = std::move(payload);
  }
  s->offset = 0;
  s->remaining = h.num_records;
  return 0;
}

// returns record length (malloc'd into *out); -1 EOF; -2 format err; -3 CRC err
long ptq_recordio_next(void* sp, char** out) {
  auto* s = static_cast<Scanner*>(sp);
  if (s->remaining == 0) {
    int rc = load_chunk(s);
    if (rc != 0) return rc;
  }
  uint32_t len;
  memcpy(&len, s->chunk.data() + s->offset, sizeof(len));
  s->offset += sizeof(len);
  *out = static_cast<char*>(malloc(len));
  memcpy(*out, s->chunk.data() + s->offset, len);
  s->offset += len;
  s->remaining--;
  return static_cast<long>(len);
}

void ptq_recordio_scanner_close(void* sp) {
  auto* s = static_cast<Scanner*>(sp);
  fclose(s->f);
  delete s;
}

// ---------------------------------------------------------------------------
// ThreadPool (framework/threadpool.h analogue) — runs C callbacks; the
// Python side uses it through the prefetch pipeline below.
// ---------------------------------------------------------------------------

struct Pool {
  std::vector<std::thread> workers;
  std::deque<std::function<void()>> tasks;
  std::mutex mu;
  std::condition_variable cv;
  bool stop = false;
};

void* ptq_pool_create(int num_threads) {
  auto* p = new Pool();
  for (int i = 0; i < num_threads; ++i) {
    p->workers.emplace_back([p] {
      for (;;) {
        std::function<void()> task;
        {
          std::unique_lock<std::mutex> lk(p->mu);
          p->cv.wait(lk, [p] { return p->stop || !p->tasks.empty(); });
          if (p->stop && p->tasks.empty()) return;
          task = std::move(p->tasks.front());
          p->tasks.pop_front();
        }
        task();
      }
    });
  }
  return p;
}

typedef void (*ptq_task_fn)(void* arg);

void ptq_pool_submit(void* pp, ptq_task_fn fn, void* arg) {
  auto* p = static_cast<Pool*>(pp);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->tasks.emplace_back([fn, arg] { fn(arg); });
  }
  p->cv.notify_one();
}

void ptq_pool_destroy(void* pp) {
  auto* p = static_cast<Pool*>(pp);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv.notify_all();
  for (auto& t : p->workers) t.join();
  delete p;
}


// ---------------------------------------------------------------------------
// Framed-TCP transport (the gRPC byte-transport role for pserver mode:
// reference operators/distributed/grpc_client.h + grpc_server.cc do the
// wire handling in C++, request handlers live above).  Frames are
// u32-length-prefixed byte bodies; partial reads/writes handled here so
// the Python layer above never loops on syscalls.
// ---------------------------------------------------------------------------

struct Conn { int fd; };
struct Listener { int fd; };

static int write_all(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return -1;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return 0;
}

static int read_all(int fd, char* p, size_t n) {
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r == 0) return 1;  // eof
    if (r < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return 0;
}

void* ptq_conn_connect(const char* host, int port, double timeout_s) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  while (::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                   sizeof(addr)) != 0) {
    ::close(fd);
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Conn{fd};
  return c;
}

int ptq_conn_send_frame(void* cp, const char* body, size_t len) {
  auto* c = static_cast<Conn*>(cp);
  uint32_t n = static_cast<uint32_t>(len);
  // one buffer, one write: header+body in a single TCP segment under
  // TCP_NODELAY (two send() calls would emit two packets per frame)
  char* buf = static_cast<char*>(malloc(len + 4));
  if (!buf) return -1;
  memcpy(buf, &n, 4);  // little-endian hosts (x86/ARM TPU VMs)
  memcpy(buf + 4, body, len);
  int rc = write_all(c->fd, buf, len + 4);
  free(buf);
  return rc;
}

// Scatter-gather frame send: the u32 length prefix plus every caller
// buffer goes to the kernel through writev — tensor bytes leave the
// ndarray with NO userspace concat copy (the grpc_serde.cc:35 zero-copy
// ByteBuffer role).  Partial writes advance the iovec in place; iovec
// batches are capped well under IOV_MAX.
int ptq_conn_send_frame_vec(void* cp, void** bufs, const size_t* lens,
                            size_t nbufs) {
  auto* c = static_cast<Conn*>(cp);
  size_t total = 0;
  for (size_t i = 0; i < nbufs; ++i) total += lens[i];
  uint32_t n = static_cast<uint32_t>(total);
  char hdr[4];
  memcpy(hdr, &n, 4);  // little-endian hosts (x86/ARM TPU VMs)

  std::vector<iovec> iov;
  iov.reserve(nbufs + 1);
  iov.push_back({hdr, 4});
  for (size_t i = 0; i < nbufs; ++i) {
    if (lens[i] == 0) continue;
    iov.push_back({bufs[i], lens[i]});
  }
  size_t idx = 0;
  while (idx < iov.size()) {
    size_t cnt = iov.size() - idx;
    if (cnt > 512) cnt = 512;  // stay under IOV_MAX everywhere
    msghdr msg{};
    msg.msg_iov = &iov[idx];
    msg.msg_iovlen = cnt;
    ssize_t w = ::sendmsg(c->fd, &msg, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    size_t done = static_cast<size_t>(w);
    while (idx < iov.size() && done >= iov[idx].iov_len) {
      done -= iov[idx].iov_len;
      ++idx;
    }
    if (idx < iov.size() && done) {
      iov[idx].iov_base = static_cast<char*>(iov[idx].iov_base) + done;
      iov[idx].iov_len -= done;
    }
  }
  return 0;
}

char* ptq_conn_recv_frame(void* cp, size_t* len_out) {
  auto* c = static_cast<Conn*>(cp);
  char hdr[4];
  int r = read_all(c->fd, hdr, 4);
  if (r != 0) return nullptr;
  uint32_t n;
  memcpy(&n, hdr, 4);
  char* buf = static_cast<char*>(malloc(n ? n : 1));
  if (!buf) return nullptr;
  if (read_all(c->fd, buf, n) != 0) {
    free(buf);
    return nullptr;
  }
  *len_out = n;
  return buf;  // caller frees via ptq_buffer_free
}

void ptq_conn_shutdown(void* cp) {
  // wake a blocked reader WITHOUT freeing: the serving thread owns the
  // handle and closes it when its recv returns EOF
  auto* c = static_cast<Conn*>(cp);
  ::shutdown(c->fd, SHUT_RDWR);
}

void ptq_conn_close(void* cp) {
  auto* c = static_cast<Conn*>(cp);
  ::shutdown(c->fd, SHUT_RDWR);
  ::close(c->fd);
  delete c;
}

void* ptq_listener_create(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  return new Listener{fd};
}

int ptq_listener_port(void* lp) {
  auto* l = static_cast<Listener*>(lp);
  sockaddr_in addr{};
  socklen_t alen = sizeof(addr);
  if (::getsockname(l->fd, reinterpret_cast<sockaddr*>(&addr), &alen) != 0)
    return -1;
  return ntohs(addr.sin_port);
}

void ptq_listener_shutdown(void* lp) {
  // wake a blocked accept WITHOUT freeing; the accept loop owns the
  // listener and closes it when accept returns failure
  auto* l = static_cast<Listener*>(lp);
  ::shutdown(l->fd, SHUT_RDWR);
}

void* ptq_listener_accept(void* lp) {
  auto* l = static_cast<Listener*>(lp);
  int fd;
  do {
    fd = ::accept(l->fd, nullptr, nullptr);
  } while (fd < 0 && (errno == EINTR || errno == ECONNABORTED));
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return new Conn{fd};
}

void ptq_listener_close(void* lp) {
  auto* l = static_cast<Listener*>(lp);
  ::shutdown(l->fd, SHUT_RDWR);
  ::close(l->fd);
  delete l;
}

}  // extern "C"
