"""Benchmark: Transformer-base training throughput (tokens/sec) on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Model: Transformer-base (d_model=512, 8 heads, ffn 2048, 6+6 layers,
vocab 32k, seq 64) — the reference's dist_transformer.py config — built and
trained entirely through the paddle_tpu program stack (layer DSL →
append_backward → Adam ops → whole-block XLA lowering).

Baseline for vs_baseline: 50,000 tokens/sec ≈ A100 mixed-precision
Transformer-base training per-chip throughput (BASELINE.md north-star:
"≥A100 per-chip throughput").
"""
from __future__ import annotations

import json
import time

import numpy as np

A100_TOKENS_PER_SEC = 50_000.0

BATCH = 128
SEQ = 64
VOCAB = 32000
WARMUP = 3
STEPS = 20
DTYPE = "bfloat16"


def main():
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.executor import Executor, Scope, scope_guard
    from paddle_tpu.core.lowering import analyze_block, build_block_fn
    from paddle_tpu.core.program import Program, program_guard
    from paddle_tpu.models import transformer

    prog, startup = Program(), Program()
    prog.random_seed = 1
    with program_guard(prog, startup), unique_name.guard():
        feed_names, loss, _ = transformer.build(
            src_vocab=VOCAB, tgt_vocab=VOCAB, max_len=SEQ,
            dropout=0.1, with_optimizer=True, dtype=DTYPE,
            attention_impl="auto")

    scope = Scope()
    exe = Executor()
    with scope_guard(scope):
        exe.run(startup)

        rng_np = np.random.RandomState(0)
        mask = np.ones((BATCH, SEQ), "float32")
        feed = {
            "src_ids": rng_np.randint(0, VOCAB, (BATCH, SEQ)).astype("int64"),
            "tgt_ids": rng_np.randint(0, VOCAB, (BATCH, SEQ)).astype("int64"),
            "lbl_ids": rng_np.randint(0, VOCAB, (BATCH, SEQ)).astype("int64"),
            "src_mask": mask,
            "tgt_mask": mask,
        }
        ordered = sorted(feed)
        plan = analyze_block(prog, 0, ordered, [loss.name])
        fn = build_block_fn(prog, plan)
        jitted = jax.jit(fn, donate_argnums=(1,))

        feeds = [jax.device_put(feed[n]) for n in ordered]
        donated = [jax.device_put(np.asarray(scope.find_var(n)))
                   for n in plan.donated_reads]
        const = [jax.device_put(np.asarray(scope.find_var(n)))
                 for n in plan.const_reads]
        rng = jax.random.PRNGKey(0)

        refeed = plan.donated_write_indices

        def step(donated, rng):
            fetches, new_state, rng = jitted(feeds, donated, const, rng)
            return fetches[0], [new_state[i] for i in refeed], rng

        for _ in range(WARMUP):
            l, donated, rng = step(donated, rng)
        jax.block_until_ready(l)

        t0 = time.time()
        for _ in range(STEPS):
            l, donated, rng = step(donated, rng)
        jax.block_until_ready(l)
        dt = time.time() - t0

    tokens_per_sec = BATCH * SEQ * STEPS / dt
    print(json.dumps({
        "metric": "transformer_base_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tokens_per_sec / A100_TOKENS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
