"""Benchmark: all five BASELINE configs on one chip, one JSON line.

Tunnel-robust harness (round 5): the parent process NEVER imports jax.
It (1) probes the TPU tunnel in a kill-able subprocess and records the
measured RTT in the artifact, (2) runs the configs in a worker
subprocess that prints one flushed partial JSON line per completed
config (an external timeout therefore loses at most the in-flight
config, not the finished ones), (3) enforces a total wall-clock budget
(PADDLE_TPU_BENCH_BUDGET_S, default 1200 s) and a per-config deadline —
a hung config is killed, marked {"error": "timeout"}, and the worker is
restarted on the remaining configs, (4) always prints the final
combined JSON line itself, with explicit {"skipped": "budget"} /
{"skipped": "tunnel probe failed"} markers for anything not run,
(5) writes a per-config runtime-telemetry artifact (step_stats.json;
path override PADDLE_TPU_BENCH_STATS_PATH, empty disables):
compile-cache hits/misses, lowering + XLA compile time and feed/fetch
bytes from paddle_tpu.observability, so a BENCH_r*.json regression
carries its own explanation.  The rpc_transport config additionally
writes a sampled-trace artifact (bench_trace.json; path override
PADDLE_TPU_BENCH_TRACE_PATH, empty disables): one traced batched round
as a Chrome/Perfetto trace, so the wire spans are inspectable per run.
Role analogue: the reference benchmark driver emits numbers as it goes
(benchmark/fluid/fluid_benchmark.py:295 print_train_time), not at exit.

Round 7 adds the perf-attribution chain: each bench_program config AOT
lower()+compile()s its executable (the same one jax.jit would build) so
XLA ``cost_analysis()`` flops/bytes land next to the measured rate as a
``roofline`` entry (achieved vs peak FLOP/s and GB/s,
compute-vs-memory-bound — observability/perf.py arithmetic), and the
final summary auto-compares against the last *measured* BENCH_r*.json
round via tools/bench_compare.py, recording per-config deltas with
noise bands and a regression verdict under ``comparison``
(PADDLE_TPU_BENCH_COMPARE_PREV pins a baseline, empty disables).

Primary metric (the BASELINE.json headline): ResNet-50 train images/sec/
chip (bf16, batch 256) vs an A100 mixed-precision baseline (~2,500
img/s).  The ``configs`` field carries the other four:

- transformer: Transformer-base at seq 256 with attention-prob dropout
  (auto attention impl: XLA fused attention at this length — the Pallas
  flash kernel takes over at seq >= 2048 where O(T^2) scores would
  dominate HBM), tokens/sec vs A100 ~50k
- stacked_lstm: 3-layer LSTM sentiment net over padded length-128
  sequences, tokens/sec
- deepfm: CTR model with a 1M-row sparse (SelectedRows) embedding table,
  samples/sec
- mnist: convnet, images/sec

Each config reports an approximate model-FLOPs utilization (``mfu_est``)
against the v5e bf16 peak (197 TFLOP/s) where the arithmetic is dense
enough for the estimate to mean something.

All models run through the full paddle_tpu program stack (layer DSL →
append_backward → optimizer ops → whole-block XLA lowering); the bench
drives the jitted step directly with device-resident donated state, the
steady-state training loop.
"""
from __future__ import annotations

import json
import time

import numpy as np

V5E_BF16_PEAK = 197e12
WARMUP = 3
STEPS = 12

# set by bench_program from the AOT-compiled executable's XLA
# cost_analysis + the measured dispatch time; _take_roofline() moves it
# into the finishing config's result so every BENCH_r*.json throughput
# number ships with flops/bytes attribution and a roofline position
_LAST_ROOFLINE = None


def _take_roofline():
    global _LAST_ROOFLINE
    r, _LAST_ROOFLINE = _LAST_ROOFLINE, None
    return r


def _harvest_roofline(compiled, seconds_per_dispatch):
    """XLA cost attribution for one timed executable: flops + bytes
    accessed from ``cost_analysis()`` and the achieved-vs-peak roofline
    numbers (observability/perf.py arithmetic — per-dispatch flops over
    per-dispatch seconds, so the K-step scan normalization cancels).
    Attribution must never take the bench down."""
    global _LAST_ROOFLINE
    try:
        from paddle_tpu.observability import perf as _perf
        cost = _perf.cost_dict(compiled)
        flops = float(cost.get("flops", 0.0) or 0.0)
        bytes_acc = float(cost.get("bytes accessed", 0.0) or 0.0)
        rf = {"flops_per_dispatch": flops,
              "bytes_per_dispatch": bytes_acc}
        rf.update(_perf.roofline_numbers(flops, bytes_acc,
                                         seconds_per_dispatch))
        _LAST_ROOFLINE = rf
    except Exception:
        _LAST_ROOFLINE = None


def two_point_fit(timed):
    """Per-dispatch device time from a two-point RTT-cancelling fit.

    The tunnel's per-readback round trip is ~1.4 s (r3 measurement: K=8
    and K=192 matmul scans take the same wall time), so a single timed
    call measures mostly RTT.  Back-to-back dispatches pipeline on
    device; only the final readback pays the RTT, so
    t(n calls) = RTT + n*t_dispatch and the n=3 minus n=1 difference is
    2 dispatches of pure device time.  ``timed(n)`` runs n back-to-back
    dispatches and returns wall seconds.

    Reps: RTT noise is ±several hundred ms, so each point takes the MIN
    over several samples, interleaved (1,3,1,3,...) so a slow-network
    window hits both points rather than biasing one side of the fit."""
    t1s, t3s = [], []
    for _ in range(3):
        t1s.append(timed(1))
        t3s.append(timed(3))
    t1s.append(timed(1))
    t1, t3 = min(t1s), min(t3s)
    dt = t3 - t1
    if dt <= 0:  # noise swamped the fit; conservative fallback
        return t3 / 3
    return dt / 2


def bench_program(prog, startup, feed, fetch_names, steps=STEPS,
                  warmup=WARMUP, scan_steps=None):
    """Steady-state steps/sec for one program (donated device state).

    ``scan_steps=K`` runs K optimizer steps per dispatch via ``lax.scan``
    (the device-side training loop — amortizes host dispatch the way a
    production TPU loop double-buffers it away); per-step RNG still
    advances so dropout differs step to step.  When ``scan_steps`` is
    set, ``steps``/``warmup`` are ignored — timing is 1 warmup dispatch
    plus two_point_fit's interleaved sample schedule (4x n=1 and 3x n=3
    timed dispatch batches, min-per-point, n=3 minus n=1 fit).
    """
    import jax
    from jax import lax
    from paddle_tpu.core.executor import (Executor, Scope, _as_device_array,
                                          scope_guard)
    from paddle_tpu.core.lowering import analyze_block, build_block_fn

    scope = Scope()
    exe = Executor()
    with scope_guard(scope):
        exe.run(startup)

        ordered = sorted(feed)
        plan = analyze_block(prog, 0, ordered, list(fetch_names))
        fn = build_block_fn(prog, plan)
        refeed = plan.donated_write_indices

        block = prog.global_block
        feeds = [jax.device_put(
            _as_device_array(feed[n], block.var_or_none(n)))
            for n in ordered]
        donated = [jax.device_put(np.asarray(scope.find_var(n)))
                   for n in plan.donated_reads]
        const = [jax.device_put(np.asarray(scope.find_var(n)))
                 for n in plan.const_reads]
        rng = jax.random.PRNGKey(0)

        if scan_steps:
            K = scan_steps

            def multi(feeds, donated, const, rng):
                def one(carry, _):
                    donated, rng = carry
                    fetches, new_state, rng = fn(feeds, donated, const, rng)
                    return ([new_state[i] for i in refeed], rng), fetches[0]
                (donated, rng), ls = lax.scan(
                    one, (donated, rng), None, length=K)
                return ls[-1], donated, rng

            # AOT lower+compile the SAME executable jax.jit would build:
            # the compiled handle exposes cost_analysis() for the
            # roofline attribution the summary carries per config
            compiled = jax.jit(multi, donate_argnums=(1,)).lower(
                feeds, donated, const, rng).compile()

            def step(donated, rng):
                return compiled(feeds, donated, const, rng)

            l, donated, rng = step(donated, rng)  # warmup: settle + K steps
            float(np.asarray(l))

            def timed(n):
                nonlocal donated, rng
                t0 = time.perf_counter()
                l = None
                for _ in range(n):
                    l, donated, rng = step(donated, rng)
                float(np.asarray(l))
                return time.perf_counter() - t0

            dt = two_point_fit(timed)
            _harvest_roofline(compiled, dt)
            return K / dt

        compiled = jax.jit(fn, donate_argnums=(1,)).lower(
            feeds, donated, const, rng).compile()  # AOT: analyzable handle

        def step(donated, rng):
            fetches, new_state, rng = compiled(feeds, donated, const, rng)
            return fetches[0], [new_state[i] for i in refeed], rng

        l = None
        for _ in range(warmup):
            l, donated, rng = step(donated, rng)
        if l is not None:
            float(np.asarray(l))  # hard sync: block_until_ready is
        t0 = time.perf_counter()  # unreliable through the remote tunnel
        for _ in range(steps):
            l, donated, rng = step(donated, rng)
        float(np.asarray(l))
        dt = time.perf_counter() - t0
        _harvest_roofline(compiled, dt / steps)
    return steps / dt


def _fresh(build_fn, seed=1):
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.program import Program, program_guard

    prog, startup = Program(), Program()
    prog.random_seed = seed
    with program_guard(prog, startup), unique_name.guard():
        out = build_fn()
    return prog, startup, out


def bench_resnet50():
    from paddle_tpu.models import resnet

    B = 256  # best measured batch for v5e-1 (128: 2.1k, 512: 2.4k img/s)
    prog, startup, (feeds, loss, acc) = _fresh(
        lambda: resnet.build(dtype="bfloat16", lr=0.1, layout="NHWC"))
    rng = np.random.RandomState(0)
    feed = {"data": rng.randn(B, 3, 224, 224).astype("float32"),
            "label": rng.randint(0, 1000, (B, 1)).astype("int64")}
    sps = bench_program(prog, startup, feed, [loss.name], steps=96,
                        scan_steps=96)
    img_s = sps * B
    flops_per_img = 3 * 3.8e9  # fwd 3.8 GF @224 x ~3 for fwd+bwd
    return {"images_per_sec": round(img_s, 1),
            "mfu_est": round(img_s * flops_per_img / V5E_BF16_PEAK, 3)}


def bench_transformer():
    from paddle_tpu.models import transformer

    B, T, V, D, L = 32, 256, 32000, 512, 6
    prog, startup, (feeds, loss, _) = _fresh(
        lambda: transformer.build(src_vocab=V, tgt_vocab=V, max_len=T,
                                  dropout=0.1, dtype="bfloat16",
                                  attention_impl="auto"))
    rng = np.random.RandomState(0)
    mask = np.ones((B, T), "float32")
    feed = {"src_ids": rng.randint(0, V, (B, T)).astype("int64"),
            "tgt_ids": rng.randint(0, V, (B, T)).astype("int64"),
            "lbl_ids": rng.randint(0, V, (B, T)).astype("int64"),
            "src_mask": mask, "tgt_mask": mask}
    sps = bench_program(prog, startup, feed, [loss.name], steps=24,
                        scan_steps=24)
    tok_s = sps * B * T
    # ~63M non-embedding params; attention scores: 18 attn blocks
    flops_per_step = (6 * 63e6 * B * T * 2  # enc+dec streams share tokens
                      + 12 * 18 * B * T * T * D)
    return {"tokens_per_sec": round(tok_s, 1),
            "mfu_est": round(sps * flops_per_step / V5E_BF16_PEAK, 3)}


def bench_stacked_lstm():
    from paddle_tpu.models import stacked_lstm

    B, T = 128, 128
    prog, startup, (feeds, loss, acc) = _fresh(
        lambda: stacked_lstm.build(dict_dim=30000, emb_dim=512, hid_dim=512,
                                   stacked_num=3))
    rng = np.random.RandomState(0)
    feed = {"words": rng.randint(0, 30000, (B, T, 1)).astype("int64"),
            "words@LEN": np.full((B,), T, "int64"),
            "label": rng.randint(0, 2, (B, 1)).astype("int64")}
    sps = bench_program(prog, startup, feed, [loss.name], steps=24,
                        scan_steps=24)
    tok_s = sps * B * T
    # per token per layer: 8*H*H matmul flops, x3 train
    flops_per_step = 3 * 2 * (8 * 512 * 512) * 3 * B * T
    return {"tokens_per_sec": round(tok_s, 1),
            "mfu_est": round(sps * flops_per_step / V5E_BF16_PEAK, 3)}


def bench_deepfm():
    from paddle_tpu.models import deepfm

    B = 2048
    rows = 1_000_000
    prog, startup, (feeds, loss, _) = _fresh(
        lambda: deepfm.build(sparse_dim=rows))
    rng = np.random.RandomState(0)
    feed = {"dense": rng.randn(B, 13).astype("float32"),
            "sparse": rng.randint(0, rows, (B, 26)).astype("int64"),
            "label": rng.randint(0, 2, (B, 1)).astype("float32")}
    sps = bench_program(prog, startup, feed, [loss.name], steps=24,
                        scan_steps=24)
    out = {"samples_per_sec": round(sps * B, 1), "table_rows": rows}
    out["raw_jax_floor_samples_per_sec"] = _deepfm_scatter_floor(B, rows)
    out["vs_floor"] = round(out["samples_per_sec"]
                            / max(out["raw_jax_floor_samples_per_sec"], 1), 3)
    return out


def _deepfm_scatter_floor(B, rows, emb_dim=10, slots=26, K=24):
    """Raw-JAX floor for the sparse part of the CTR step, WORKLOAD-
    MATCHED to the model: BOTH tables ([rows, emb] second-order and
    [rows, 1] first-order) each do an embedding gather over the same
    B*slots ids + a grad scatter — the irreducible per-step table
    traffic with no framework anywhere (the r3 floor used ONE table and
    so overstated the gap ~1.26x).  Same K-scan + two-point RTT fit as
    bench_program."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.RandomState(1)
    t_emb = jnp.asarray(rng.randn(rows, emb_dim) * 0.01, jnp.float32)
    t_w1 = jnp.asarray(rng.randn(rows, 1) * 0.01, jnp.float32)
    flat = jnp.asarray(rng.randint(0, rows, (B * slots,)))

    @jax.jit
    def multi(state):
        def body(state, _):
            t_emb, t_w1 = state
            e = t_emb[flat]                          # gather [B*slots, emb]
            e1 = t_w1[flat]
            t_emb = t_emb.at[flat].add(-0.01 * 2.0 * e)  # scatter-SGD
            t_w1 = t_w1.at[flat].add(-0.01 * 2.0 * e1)
            return (t_emb, t_w1), None
        state, _ = lax.scan(body, state, None, length=K)
        return state

    r = multi((t_emb, t_w1))
    float(np.asarray(r[0][0, 0]))

    def timed(n):
        nonlocal r
        t0 = time.perf_counter()
        for _ in range(n):
            r = multi(r)
        float(np.asarray(r[0][0, 0]))
        return time.perf_counter() - t0

    dt = two_point_fit(timed) / K
    return round(B / dt, 1)


def bench_deepfm_fused():
    """ISSUE 10 / ROADMAP 3(c): the fused Pallas sparse-embedding path
    (FLAGS_sparse_fused_kernel — one multi-table gather launch + one
    row-wise update launch per table, kernels/sparse.py) vs the
    masked-dense baseline vs the workload-matched raw-JAX two-table
    floor, ``vs_floor`` inline.  On-chip target: >= 400k samples/s,
    >= 0.8x the floor-band center (PERF.md §11).

    Off-TPU this config cannot measure the claim (interpret-mode grids
    are ~600 us/row on CPU), so it degrades to a structural analysis
    artifact labeled ``analysis: true``: the whole-step scatter-class /
    pallas-launch census plus a small-shape fused-vs-unfused parity
    check — the shape of the evidence, while the number waits for the
    tunnel (ROADMAP item 5 capture list)."""
    import jax

    if jax.default_backend() != "tpu":
        return _deepfm_fused_analysis()

    from paddle_tpu.core import flags as _flags
    from paddle_tpu.models import deepfm

    B = 2048
    rows = 1_000_000
    rng = np.random.RandomState(0)
    feed = {"dense": rng.randn(B, 13).astype("float32"),
            "sparse": rng.randint(0, rows, (B, 26)).astype("int64"),
            "label": rng.randint(0, 2, (B, 1)).astype("float32")}

    def run(flag):
        _flags.set_flags({"sparse_fused_kernel": flag})
        try:
            prog, startup, (feeds, loss, _) = _fresh(
                lambda: deepfm.build(sparse_dim=rows))
            return bench_program(prog, startup, feed, [loss.name], steps=24,
                                 scan_steps=24)
        finally:
            _flags.set_flags({"sparse_fused_kernel": False})

    dense_sps = run(False)
    fused_sps = run(True)  # last: the harvested roofline is the fused step
    floor = _deepfm_scatter_floor(B, rows)
    return {
        "fused_samples_per_sec": round(fused_sps * B, 1),
        "masked_dense_samples_per_sec": round(dense_sps * B, 1),
        "table_rows": rows,
        "raw_jax_floor_samples_per_sec": floor,
        "vs_floor": round(fused_sps * B / max(floor, 1), 3),
        "vs_masked_dense": round(fused_sps / max(dense_sps, 1e-9), 3),
    }


def _deepfm_fused_analysis():
    """CPU degrade of ``bench_deepfm_fused``: structural evidence only."""
    import jax

    from paddle_tpu.core import flags as _flags
    from paddle_tpu.core.executor import Executor, Scope, scope_guard
    from paddle_tpu.core.lowering import analyze_block, build_block_fn
    from paddle_tpu.core.program import Program, program_guard
    from paddle_tpu.core import unique_name
    from paddle_tpu.models import deepfm

    from paddle_tpu.kernels.sparse import jaxpr_census as census

    B, rows = 8, 512

    def step(flag, n_steps=0):
        _flags.set_flags({"sparse_fused_kernel": flag})
        try:
            prog, startup = Program(), Program()
            prog.random_seed = 3
            with program_guard(prog, startup), unique_name.guard():
                feeds, loss, _ = deepfm.build(sparse_dim=rows, lr=1e-2)
            rng = np.random.RandomState(0)
            feed = {"dense": rng.rand(B, 13).astype("float32"),
                    "sparse": rng.randint(0, rows, (B, 26)).astype("int64"),
                    "label": (rng.rand(B, 1) > 0.5).astype("float32")}
            exe = Executor()
            sc = Scope()
            with scope_guard(sc):
                exe.run(startup)
                plan = analyze_block(prog, 0, sorted(feeds), [loss.name])
                fn = build_block_fn(prog, plan, training=True)
                fv = [feed[n] for n in sorted(feeds)]
                donated = [np.asarray(sc.find_var(n))
                           for n in plan.donated_reads]
                const = [np.asarray(sc.find_var(n))
                         for n in plan.const_reads]
                jaxpr = jax.make_jaxpr(fn)(fv, donated, const,
                                           jax.random.PRNGKey(0))
                table = None
                for _ in range(n_steps):
                    exe.run(prog, feed=feed, fetch_list=[loss.name])
                if n_steps:
                    table = np.asarray(sc.find_var("ctr.sparse_emb")).copy()
            return census(jaxpr.jaxpr), table
        finally:
            _flags.set_flags({"sparse_fused_kernel": False})

    (sc_on, pl_on), t_on = step(True, n_steps=2)
    (sc_off, pl_off), t_off = step(False, n_steps=2)
    return {
        "analysis": True,
        "note": "CPU structural run: interpret-mode kernels cannot measure "
                "the on-chip rate; capture deepfm_fused on a live tunnel",
        "scatter_ops_flag_on": sc_on,
        "scatter_ops_flag_off": sc_off,
        "pallas_launches_flag_on": pl_on,
        "fused_parity_maxdiff": float(np.max(np.abs(t_on - t_off))),
        "table_rows": rows,
    }


def bench_resnet50_datapath():
    """ResNet-50 with the DATA LAYER on the hot path: batches flow
    native RecordIO file -> C MPMC queue -> DataLoader (device_prefetch
    one batch ahead) -> per-step async ``exe.run`` — the reference's
    double-buffer reader train loop
    (operators/reader/create_double_buffer_reader_op.cc,
    benchmark/fluid/fluid_benchmark.py:137).

    On this tunneled chip the HONEST bound is the link, not the model:
    host->device tops out at ~20 MB/s (measured inline below), which
    caps ANY fresh-data feed at ~130 img/s f32 — pre-staged feeds are
    how the main bench isolates device throughput.  The meaningful
    metric here is pipeline efficiency: measured datapath rate vs the
    raw ``jax.device_put`` ceiling for the same bytes.  >=0.8 means
    RecordIO+queue+decode+dispatch add <20% on top of the link."""
    import os
    import tempfile

    import jax

    from paddle_tpu.core.executor import Executor, Scope, scope_guard
    from paddle_tpu.data.loader import DataLoader
    from paddle_tpu.data.recordio_utils import reader_creator, write_recordio
    from paddle_tpu.models import resnet

    B, n_batches, steps = 32, 4, 20
    rng = np.random.RandomState(0)
    batches = [(rng.randn(B, 3, 224, 224).astype("float32"),
                rng.randint(0, 1000, (B, 1)).astype("int64"))
               for _ in range(n_batches)]

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "resnet.recordio")

        def sample_reader():
            for img, lbl in batches:
                for i in range(B):
                    yield (img[i], lbl[i])

        write_recordio(sample_reader, path)

        def batch_reader():
            while True:  # cycle forever; bench takes `steps` batches
                buf = []
                for sample in reader_creator(path)():
                    buf.append(sample)
                    if len(buf) == B:
                        yield buf
                        buf = []

        prog, startup, (feeds, loss, acc) = _fresh(
            lambda: resnet.build(dtype="bfloat16", lr=0.1, layout="NHWC"))
        scope = Scope()
        exe = Executor()
        with scope_guard(scope):
            exe.run(startup)
            loader = DataLoader(feed_list=["data", "label"],
                                reader=batch_reader, capacity=2,
                                program=prog)
            it = iter(loader)
            # warmup: compile + settle the queue
            feed = next(it)
            l, = exe.run(prog, feed=feed, fetch_list=[loss.name])
            float(np.asarray(l))

            t0 = time.perf_counter()
            last = None
            for _ in range(steps):
                feed = next(it)
                last, = exe.run(prog, feed=feed, fetch_list=[loss.name])
            float(np.asarray(last))      # one batched flush (async run)
            dt = time.perf_counter() - t0
        datapath_img_s = steps * B / dt

        # raw link ceiling: device_put the same bytes, nothing else
        arrs = [b[0] for b in batches]
        d = jax.device_put(arrs[0])
        float(np.asarray(d.ravel()[0]))
        t0 = time.perf_counter()
        ds = [jax.device_put(arrs[i % n_batches]) for i in range(steps)]
        for d in ds:
            d.block_until_ready()
        float(np.asarray(ds[-1].ravel()[0]))
        link_img_s = steps * B / (time.perf_counter() - t0)

    return {"images_per_sec": round(datapath_img_s, 1),
            "link_serial_put_images_per_sec": round(link_img_s, 1),
            "pipeline_vs_link": round(datapath_img_s / link_img_s, 3),
            "note": "tunnel H2D ~20MB/s caps fresh-data feeds at ~2% of "
                    "the pre-staged 2,600 img/s; pipeline_vs_link >= 1 "
                    "means RecordIO+queue+decode+async-dispatch saturate "
                    "the link (overlapped transfers beat the serial "
                    "device_put probe) — the data layer is not the bound"}


def bench_mnist():
    from paddle_tpu.models import mnist

    B = 512
    prog, startup, (feeds, loss, acc) = _fresh(lambda: mnist.build())
    rng = np.random.RandomState(0)
    feed = {"pixel": rng.randn(B, 1, 28, 28).astype("float32"),
            "label": rng.randint(0, 10, (B, 1)).astype("int64")}
    # K=384: the mnist step is ~0.3 ms, so short scans leave the fit
    # dominated by dispatch jitter (r3/r4 runs swung 0.8-1.7M img/s);
    # a longer in-jit scan amortizes it to band noise
    sps = bench_program(prog, startup, feed, [loss.name], steps=384,
                        scan_steps=384)
    return {"images_per_sec": round(sps * B, 1)}


def bench_flash_attention_long():
    """Long-context attention: Pallas flash fwd+bwd at seq 8192 (XLA's
    materialized-scores path fails to compile at this length on v5e —
    flash is the only viable kernel; its O(block) memory is the
    long-context story).

    Two shapes at equal FLOPs / model width: H=8,D=64 and the TPU-native
    H=4,D=128 (head_dim = MXU lane width halves the per-score VPU
    softmax work).  Timing: K-step in-jit scan, n=3 minus n=1 dispatch
    fit (see bench_program) — single-dispatch timings here are ~95%
    tunnel RTT."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.kernels.attention import flash_attention

    T, K = 8192, 12
    out = {"seq_len": T}
    best = 0.0
    for tag, (B, H, D) in {"h8_d64": (4, 8, 64),
                           "h4_d128": (4, 4, 128)}.items():
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
        k = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
        v = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)

        def loss(q, k, v):
            return (flash_attention(q, k, v, None, True, None)
                    .astype(jnp.float32) ** 2).sum()

        grad = jax.grad(loss, (0, 1, 2))

        def multi(q, k, v):
            def body(carry, _):
                q, k, v = carry
                dq, dk, dv = grad(q, k, v)
                eps = jnp.bfloat16(1e-8)
                return (q + dq * eps, k + dk * eps, v + dv * eps), None
            (q, k, v), _ = lax.scan(body, (q, k, v), None, length=K)
            return q
        step = jax.jit(multi)
        r = step(q, k, v)
        float(np.asarray(r[0, 0, 0, 0]))

        def timed(n):
            t0 = time.perf_counter()
            for _ in range(n):
                r = step(q, k, v)
            float(np.asarray(r[0, 0, 0, 0]))
            return time.perf_counter() - t0

        dt = two_point_fit(timed) / K
        flops = 3.5 * 2 * B * H * T * T * D / 2  # causal fwd+bwd
        tf = flops / dt / 1e12
        out[tag] = {"tokens_per_sec": round(B * T / dt, 1),
                    "tflops": round(tf, 1)}
        best = max(best, tf)

    # numerics cross-check at the full 8k length: chunked-jnp reference
    # (XLA's one-shot attention fails to compile at this T) on one
    # batch-head, bf16 tolerance
    @jax.jit
    def ref_slice(q, k, v):
        sm = 1.0 / np.sqrt(q.shape[-1])

        def chunk(i):
            c = lax.dynamic_slice_in_dim(q, i * 1024, 1024, 0)
            s = (c @ k.T).astype(jnp.float32) * sm
            qi = jnp.arange(1024)[:, None] + i * 1024
            s = jnp.where(qi >= jnp.arange(T)[None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            return p.astype(v.dtype) @ v
        return jnp.concatenate([chunk(i) for i in range(T // 1024)], 0)

    fl = jax.jit(lambda q, k, v: flash_attention(q, k, v, None, True, None))
    o_flash = fl(q, k, v)[0, 0]
    o_ref = ref_slice(q[0, 0], k[0, 0], v[0, 0])
    maxdiff = float(jnp.max(jnp.abs(o_flash.astype(jnp.float32)
                                    - o_ref.astype(jnp.float32))))
    assert maxdiff < 0.05, f"flash vs chunked-jnp at 8k: {maxdiff}"
    out["crosscheck_maxdiff_8k"] = round(maxdiff, 5)
    out["tflops"] = round(best, 1)
    out["tokens_per_sec"] = out["h4_d128"]["tokens_per_sec"]

    # seq-32k single-chip entry: the long-context point the ring path's
    # per-shard compute inherits (flash is O(block) memory — 32k never
    # materializes scores; XLA's chain cannot compile this length here)
    T32, K32 = 32768, 4
    B, H, D = 1, 4, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T32, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, T32, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, T32, D), jnp.bfloat16)

    def loss32(q, k, v):
        return (flash_attention(q, k, v, None, True, None)
                .astype(jnp.float32) ** 2).sum()

    grad32 = jax.grad(loss32, (0, 1, 2))

    def multi32(q, k, v):
        def body(carry, _):
            q, k, v = carry
            dq, dk, dv = grad32(q, k, v)
            eps = jnp.bfloat16(1e-8)
            return (q + dq * eps, k + dk * eps, v + dv * eps), None
        (q, k, v), _ = lax.scan(body, (q, k, v), None, length=K32)
        return q
    step32 = jax.jit(multi32)
    r = step32(q, k, v)
    float(np.asarray(r[0, 0, 0, 0]))

    def timed32(n):
        t0 = time.perf_counter()
        for _ in range(n):
            r = step32(q, k, v)
        float(np.asarray(r[0, 0, 0, 0]))
        return time.perf_counter() - t0

    dt = two_point_fit(timed32) / K32
    flops32 = 3.5 * 2 * B * H * T32 * T32 * D / 2
    out["seq32k_h4_d128"] = {"tokens_per_sec": round(B * T32 / dt, 1),
                             "tflops": round(flops32 / dt / 1e12, 1)}
    return out


def bench_ring_shard():
    """Per-shard-pair Pallas workload at the ring path's shard shapes
    (VERDICT r4 #7): with seq-parallel degree sp over global S=16384,
    each device holds S/sp=4096 queries and, per ring hop, runs flash
    against one 4096-key shard — causal-masked on the diagonal hop
    (kv_index == q_index), full unmasked on off-diagonal hops where
    kv_index < q_index.  Measuring both hop kinds on the real chip
    gives the sp-scaling story a per-shard rate: a full ring step is
    1 diagonal + (sp-1 on average /2...) — we report each hop's rate
    and the implied per-device rate for sp=4.  Correctness of the
    ring composition itself is pinned by the CPU-mesh parity tests
    (tests/test_attention.py); this entry is the missing perf anchor."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.kernels.attention import flash_attention

    S, B, H, D, K = 4096, 1, 4, 128, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, S, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, S, D), jnp.bfloat16)

    out = {"shard_len": S, "heads": H, "head_dim": D}
    for tag, causal in [("diagonal_hop_causal", True),
                        ("offdiag_hop_full", False)]:
        def loss(q, k, v, causal=causal):
            return (flash_attention(q, k, v, None, causal, None)
                    .astype(jnp.float32) ** 2).sum()

        grad = jax.grad(loss, (0, 1, 2))

        def multi(q, k, v):
            def body(carry, _):
                q, k, v = carry
                dq, dk, dv = grad(q, k, v)
                eps = jnp.bfloat16(1e-8)
                return (q + dq * eps, k + dk * eps, v + dv * eps), None
            (q, k, v), _ = lax.scan(body, (q, k, v), None, length=K)
            return q

        step = jax.jit(multi)
        r = step(q, k, v)
        float(np.asarray(r[0, 0, 0, 0]))

        def timed(n):
            t0 = time.perf_counter()
            for _ in range(n):
                r = step(q, k, v)
            float(np.asarray(r[0, 0, 0, 0]))
            return time.perf_counter() - t0

        dt = two_point_fit(timed) / K
        frac = 0.5 if causal else 1.0  # causal computes half the scores
        flops = 3.5 * 2 * B * H * S * S * D * frac
        out[tag] = {"pair_ms": round(dt * 1e3, 2),
                    "tflops": round(flops / dt / 1e12, 1)}

    # implied per-device ring step at sp=4 (1 diagonal + 1.5 avg
    # off-diagonal hops under causal load balance): tokens/s per device
    d_ms = out["diagonal_hop_causal"]["pair_ms"]
    o_ms = out["offdiag_hop_full"]["pair_ms"]
    step_ms = d_ms + 1.5 * o_ms
    out["implied_sp4_tokens_per_sec_per_device"] = round(
        B * S / (step_ms * 1e-3), 1)
    return out


def bench_rpc_transport():
    """Var-transport hot path on a loopback pserver (no TPU needed):
    measures the batched/striped/zero-copy wire (SEND_VARS/GET_VARS,
    ``FLAGS_rpc_conns_per_endpoint`` striping, sendmsg/iovec
    scatter-gather serde) against the pre-change transport shape
    (per-var SEND_VAR/GET_VAR round trips over one lock-serialized
    connection, concat-copy serde) — same server, same sockets, so the
    ratio isolates the transport work.

    Two scaling axes, two-point-fit style (min over reps):
    - ``storm_256``: 256 small dense vars per round — round-trip-count
      scaling (the many-sections model shape); metric vars/s.
    - ``dense_64mb``: one 64 MB gradient per round — copy/bandwidth
      scaling; metric effective MB/s.
    """
    import threading

    import paddle_tpu as fluid
    from paddle_tpu.distributed import serde, transport

    class _VarStore:
        """Minimal pserver-shaped service: var table behind one lock
        (the PServerLoop per-frame lock acquisition), both legacy and
        batched message types."""

        def __init__(self):
            self.vars = {}
            self.lock = threading.Lock()

        def handle(self, msg_type, tid, name, payload):
            if msg_type == transport.SEND_VAR:
                v = serde.loads_value(payload)
                with self.lock:
                    self.vars[name] = v
                return transport.OK, b""
            if msg_type == transport.SEND_VARS:
                pairs = serde.loads_batch(payload, copy=False)
                with self.lock:
                    for n, v in pairs:
                        self.vars[n] = v
                return transport.OK, b""
            if msg_type == transport.GET_VAR:
                with self.lock:
                    v = self.vars[name]
                return transport.OK, serde.dumps_value(v)
            if msg_type == transport.GET_VARS:
                names = [n for n, _ in serde.loads_batch(payload)]
                with self.lock:
                    pairs = [(n, self.vars[n]) for n in names]
                return transport.OK, serde.dumps_batch_vec(pairs)
            return transport.OK, b""

    LEGACY = {"rpc_batch_vars": 0, "rpc_vectored_io": 0,
              "rpc_conns_per_endpoint": 1, "rpc_stripe_chunk_bytes": 0}
    NEW = {"rpc_batch_vars": 1, "rpc_vectored_io": 1,
           "rpc_conns_per_endpoint": 4,
           "rpc_stripe_chunk_bytes": 8 << 20}

    def timed_min(fn, reps):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    def run_mode(flags, out, tag):
        fluid.set_flags(flags)
        srv = transport.RPCServer("127.0.0.1:0", _VarStore())
        srv.start()
        ep = f"127.0.0.1:{srv.port}"
        client = transport.RPCClient(0)
        try:
            rng = np.random.RandomState(0)
            small = [(f"v{i}", rng.randn(16).astype("float32"))
                     for i in range(256)]
            names = [n for n, _ in small]
            big = rng.randn(64 << 18).astype("float32")  # 64 MB

            def storm_send():
                if flags["rpc_batch_vars"]:
                    client.send_vars(ep, small)
                else:
                    client.parallel([(client.send_var, ep, n, v)
                                     for n, v in small])

            def storm_get():
                if flags["rpc_batch_vars"]:
                    client.get_vars(ep, names)
                else:
                    client.parallel([(client.get_var, ep, n)
                                     for n in names])

            def dense_send():
                if flags["rpc_batch_vars"]:
                    client.send_vars(ep, [("big", big)])
                else:
                    client.send_var(ep, "big", big)

            storm_send(), storm_get(), dense_send()  # warmup/connect
            t_storm = timed_min(storm_send, 5) + timed_min(storm_get, 5)
            t_dense = timed_min(dense_send, 5)
            out[f"{tag}_storm_vars_per_sec"] = round(512 / t_storm, 1)
            out[f"{tag}_dense_mb_per_sec"] = round(64 / t_dense, 1)
        finally:
            srv.stop()

    def traced_round(flags):
        """One sampled batched round AFTER timing (sampling must not
        pollute the measured numbers): the PR-3 wire spans —
        rpc.client/rpc.server send_vars/get_vars — land in the span
        ring, which _write_bench_trace turns into the trace artifact."""
        from paddle_tpu.observability import trace as _trace

        fluid.set_flags(dict(flags, trace_sample_rate=1.0))
        try:
            _trace.clear_spans()
            srv = transport.RPCServer("127.0.0.1:0", _VarStore())
            srv.start()
            ep = f"127.0.0.1:{srv.port}"
            client = transport.RPCClient(0)
            try:
                rng = np.random.RandomState(0)
                small = [(f"v{i}", rng.randn(16).astype("float32"))
                         for i in range(32)]
                with _trace.start_span("bench::rpc_round", cat="bench"):
                    client.send_vars(ep, small)
                    client.get_vars(ep, [n for n, _ in small])
            finally:
                srv.stop()
        finally:
            fluid.set_flags({"trace_sample_rate": 0.0})

    saved = fluid.get_flags(list(LEGACY) + ["trace_sample_rate"])
    out = {"storm_vars": 256, "dense_bytes": 64 << 20}
    try:
        run_mode(LEGACY, out, "legacy")
        run_mode(NEW, out, "batched")
        traced_round(NEW)
    finally:
        fluid.set_flags(saved)
    out["storm_speedup"] = round(out["batched_storm_vars_per_sec"]
                                 / out["legacy_storm_vars_per_sec"], 2)
    out["dense_speedup"] = round(out["batched_dense_mb_per_sec"]
                                 / out["legacy_dense_mb_per_sec"], 2)
    _write_bench_trace(out)
    return out


def _write_bench_trace(out):
    """Sampled-trace artifact next to step_stats.json
    (PADDLE_TPU_BENCH_TRACE_PATH overrides, empty disables): the span
    ring of the traced rpc_transport round as a Chrome/Perfetto trace,
    so the batched-wire spans are *visible* in the bench artifact, not
    just summarized."""
    import os

    path = os.environ.get("PADDLE_TPU_BENCH_TRACE_PATH", "bench_trace.json")
    if not path:
        return
    try:
        from paddle_tpu.observability import trace as _trace

        snap = _trace.local_trace_snapshot()
        if not snap["spans"]:
            return
        with open(path, "w") as f:
            json.dump(_trace.stitch_chrome_trace({"bench": snap}), f)
        out["trace_path"] = path
        out["trace_spans"] = len(snap["spans"])
    except Exception as e:  # telemetry must never take the bench down
        out["trace_error"] = repr(e)[:200]


def _serving_predictor(kind, seed=1, int8=False):
    """Forward-only predictor for the serving bench (in-process).
    ``int8=True`` runs the fusion + quantize_int8 calibration passes
    (the create_predictor enable_int8() pipeline) on the built
    program before wrapping it."""
    from paddle_tpu.core.executor import Executor, Scope, scope_guard
    from paddle_tpu.inference.predictor import Predictor

    import paddle_tpu as fluid

    if kind == "mnist":
        from paddle_tpu.models.mnist import cnn_model

        def build():
            x = fluid.layers.data("pixel", [1, 28, 28])
            return ["pixel"], cnn_model(x)
        nhwc = True  # the serving analysis pipeline's layout pass (the
        # repo's TPU-native conv layout; NCHW↔NHWC parity is pinned by
        # test_inference.py::test_convert_to_nhwc_pass_preserves_outputs)
    else:  # tiny transformer: serving-shaped, tier-1-speed geometry
        from paddle_tpu.models.transformer import transformer

        def build():
            T = 16
            src = fluid.layers.data("src_ids", [T], dtype="int64")
            tgt = fluid.layers.data("tgt_ids", [T], dtype="int64")
            sm = fluid.layers.data("src_mask", [T])
            tm = fluid.layers.data("tgt_mask", [T])
            logits = transformer(src, tgt, sm, tm, src_vocab=512,
                                 tgt_vocab=512, max_len=T, d_model=64,
                                 n_head=4, d_ffn=128, n_layer=2,
                                 dropout=0.0)
            return ["src_ids", "tgt_ids", "src_mask", "tgt_mask"], logits
        nhwc = False

    prog, startup, (feed_names, out) = _fresh(build, seed=seed)
    scope, exe = Scope(), Executor()
    with scope_guard(scope):
        exe.run(startup)
        from paddle_tpu.inference import passes as P
        if nhwc:
            P.convert_to_nhwc(prog, scope, keep_vars=[out.name])
        if int8:
            # the enable_int8() pipeline order: fusion first so the
            # int8 epilogue absorbs bias + activation
            P.fuse_fc_act(prog, scope, keep_vars=[out.name])
            P.quantize_int8(prog, scope, keep_vars=[out.name])
    return Predictor(prog, feed_names, [out.name], scope)


def _serving_request(kind, rng, rows=1):
    if kind == "mnist":
        return {"pixel": rng.randn(rows, 1, 28, 28).astype("float32")}
    T = 16
    return {"src_ids": rng.randint(0, 512, (rows, T)).astype("int64"),
            "tgt_ids": rng.randint(0, 512, (rows, T)).astype("int64"),
            "src_mask": np.ones((rows, T), "float32"),
            "tgt_mask": np.ones((rows, T), "float32")}


def _serving_load(submit_fn, requests, n_clients, window: int = 1):
    """Load generator: ``n_clients`` threads each drive its share of
    ``requests`` through ``submit_fn(feed)``.  ``window=1``:
    closed-loop synchronous (submit_fn blocks until the reply).
    ``window>1``: submit_fn returns a Future and each client keeps up
    to ``window`` requests outstanding — many concurrent remote users
    modeled with few generator threads, so the load generator's GIL
    time does not starve the 2-core bench host's XLA threads.  Returns
    (qps, p50_ms, p99_ms, errors)."""
    import threading

    lat, errors = [], []
    lock = threading.Lock()
    shards = [requests[i::n_clients] for i in range(n_clients)]

    def client(shard):
        mine = []
        pend = []
        it = iter(shard)
        done = False
        while not done or pend:
            while not done and len(pend) < window:
                feed = next(it, None)
                if feed is None:
                    done = True
                    break
                t0 = time.perf_counter()
                try:
                    r = submit_fn(feed)
                except Exception as e:
                    with lock:
                        errors.append(repr(e)[:120])
                    continue
                if window == 1:
                    mine.append((time.perf_counter() - t0) * 1e3)
                else:
                    pend.append((t0, r))
            if pend:
                t0, fut = pend.pop(0)
                try:
                    fut.result(timeout=600)
                    mine.append((time.perf_counter() - t0) * 1e3)
                except Exception as e:
                    with lock:
                        errors.append(repr(e)[:120])
        with lock:
            lat.extend(mine)

    threads = [threading.Thread(target=client, args=(s,)) for s in shards]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    lat.sort()

    def pct(p):
        return round(lat[min(int(p * len(lat)), len(lat) - 1)], 3) \
            if lat else None
    return round(len(lat) / dt, 1), pct(0.5), pct(0.99), errors


def _exec_counters():
    from paddle_tpu import observability as obs
    d = obs.stats.default_registry().to_dict()
    return {k: d.get(k, 0) for k in
            ("executor.cache_misses", "executor.shape_recompiles",
             "executor.persistent_misses")}


def bench_serving():
    """Continuous-batching serving plane vs the sequential baseline
    (paddle_tpu/serving; CPU loopback, in-process — labeled as such:
    the ratio isolates the batching/dispatch policy, the on-chip
    capture uses the same config over the tunnel).

    Per model (mnist convnet — NHWC analysis layout — and a tiny
    serving-shaped transformer):

    - ``seq``: the pre-serving shape — a server answering one request
      at a time, one ``Predictor.run`` dispatch + readback per request,
      under closed-loop concurrent clients; QPS and p50/p99 at
      saturation (p99 is dominated by queue wait, as it is for any
      serial server under load).
    - ``batched``: the same predictor behind the continuous batcher
      (warmed bucket ladder), offered ~96 outstanding requests via 8
      windowed generator threads: QPS and p50/p99.

    Plus the swap acceptance: a hot-swap under full load must complete
    with zero dropped requests and zero executor recompiles/misses in
    the post-warm serving window, and the cold vs warm-pool first-reply
    latency shows what the warm ladder buys."""
    from paddle_tpu.core import flags as _flags

    # latency anatomy + saturation anatomy ride the measured window:
    # both are host-side monotonic stamps (no device syncs); per-phase
    # p99s AND utilization/headroom land in the artifact so a tail
    # regression names its phase and a capacity shift is visible
    # round-over-round (finally-restored: a mid-bench error must not
    # leave the flags on to skew every later config in this process)
    # the golden canary probes ride the measured window too
    # (FLAGS_canary_probe at a bench cadence): goldens are recorded
    # against the live manager before load starts, so the artifact
    # carries canary_overhead_frac (what correctness probing costs) and
    # canary_failures (0 on a healthy build — a secondary gate in
    # tools/bench_compare.py)
    _flags.set_flags({"phase_attribution": True,
                      "capacity_attribution": True,
                      "canary_probe": True,
                      "canary_interval_s": 0.25})
    try:
        return _bench_serving_inner()
    finally:
        _flags.set_flags({"phase_attribution": False,
                          "capacity_attribution": False,
                          "canary_probe": False,
                          "canary_interval_s": 5.0})
        from paddle_tpu.observability import canary as _canary
        from paddle_tpu.observability import capacity as _capacity
        _canary.reset()
        _capacity.reset()


def _bench_serving_inner():
    import threading

    from paddle_tpu.serving import ModelManager

    SEQ_CLIENTS = 32
    GEN_CLIENTS, WINDOW = 8, 12
    N_REQ = {"mnist": 2560, "transformer": 640}
    BUCKETS = (1, 2, 4, 8, 16, 32)
    out = {"note": "CPU loopback, in-process (no sockets): isolates the "
                   "batching policy; on-chip capture pending tunnel",
           "seq_clients": SEQ_CLIENTS,
           "gen_clients": GEN_CLIENTS, "window": WINDOW,
           "buckets": list(BUCKETS)}
    rng = np.random.RandomState(0)

    for kind in ("mnist", "transformer"):
        pred = _serving_predictor(kind)
        requests = [_serving_request(kind, rng) for _ in range(64)]
        reqs = [requests[i % 64] for i in range(N_REQ[kind])]

        # cold first reply: fresh batcher, nothing warmed
        mgr_cold = ModelManager()
        mgr_cold.load(kind, "cold", predictor=pred, warm=False,
                      buckets=BUCKETS, activate=True, max_delay_ms=4.0)
        t0 = time.perf_counter()
        mgr_cold.infer(kind, reqs[0], timeout=600)
        cold_ms = (time.perf_counter() - t0) * 1e3
        mgr_cold.close()

        # sequential baseline: a serial server, one request start to
        # finish at a time (dispatch + readback inside the lock)
        for feed in reqs[:4]:
            np.asarray(pred.run(feed)[0])  # warm the batch-1 executable
        seq_lock = threading.Lock()

        def seq_submit(feed):
            with seq_lock:
                return np.asarray(pred.run(feed)[0])
        seq_qps, seq_p50, seq_p99, seq_err = _serving_load(
            seq_submit, reqs[:SEQ_CLIENTS * 15], SEQ_CLIENTS)

        # warm pool + continuous batching
        mgr = ModelManager()
        sm = mgr.load(kind, "1", predictor=pred, warm=True, buckets=BUCKETS,
                      activate=True, max_delay_ms=4.0,
                      max_queue_rows=8192)
        t0 = time.perf_counter()
        mgr.infer(kind, reqs[0], timeout=600)
        warm_ms = (time.perf_counter() - t0) * 1e3

        # golden canary in-window: record 2 goldens against the live
        # manager (trusted by construction: same build, same params),
        # then let the prober replay them through the REAL batcher
        # submit path concurrently with the measured load — probes are
        # tenant-tagged __canary__ so metering excludes them
        from paddle_tpu.observability import canary as _canary
        fetch = mgr.fetch_names(kind)
        cases = []
        for feed in reqs[:2]:
            outs = mgr.infer(kind, feed, timeout=600,
                             tenant=_canary.CANARY_TENANT)
            cases.append({"feeds": dict(feed),
                          "expect": list(zip(fetch, outs))})
        cp = _canary.prober()
        if cp is not None:
            cp.goldens.models[kind] = {"rtol": None, "cases": cases}
        _canary.register_target(
            f"bench/{kind}", kind,
            lambda feeds, tenant, _k=kind, _m=mgr, _f=fetch: list(zip(
                _f, _m.infer(_k, feeds, timeout=600, tenant=tenant))))
        _canary.maybe_start_from_flags()

        bat_qps, bat_p50, bat_p99, bat_err = _serving_load(
            lambda feed: mgr.submit(kind, feed),
            reqs, GEN_CLIENTS, window=WINDOW)
        # the swap below flips to a DIFFERENT predictor version — the
        # v1 goldens would (correctly) fail against v2, so the target
        # retires with its window
        _canary.unregister_target(f"bench/{kind}")

        res = {
            "seq_qps": seq_qps, "seq_p50_ms": seq_p50,
            "seq_p99_ms": seq_p99,
            "batched_qps": bat_qps, "batched_p50_ms": bat_p50,
            "batched_p99_ms": bat_p99,
            "speedup": round(bat_qps / max(seq_qps, 1e-9), 2),
            "cold_first_reply_ms": round(cold_ms, 1),
            "warm_pool_first_reply_ms": round(warm_ms, 1),
            "warm_pool": sm.warm_info,
            "dropped": len(seq_err) + len(bat_err),
        }
        rec = sm.batcher.stats.phases()
        if rec is not None:
            # where the batched p99 went: per-phase p99 + the slowest-
            # phase attribution (queue/assemble/dispatch/device/reply),
            # from ONE consistent snapshot of the live recorder
            psnap = rec.snapshot()
            res["phase_p99_ms"] = {name: ent["p99_ms"]
                                   for name, ent in psnap["phases"].items()}
            res["slowest_phase"] = psnap["slowest_phase"]
        cap = sm.batcher.stats.capacity()
        if cap is not None:
            # saturation anatomy over the measured window: which phase
            # binds, how utilized it ran, and the operational-law
            # ceiling the run implies (informational in bench_compare)
            csnap = cap.snapshot()
            res["utilization"] = csnap.get("utilization")
            res["headroom_frac"] = csnap.get("headroom_frac")
            res["binding_phase"] = csnap.get("binding_phase")
            res["predicted_max_qps"] = csnap.get("predicted_max_qps")

        if kind == "mnist":
            # hot-swap acceptance under full load: v2 warms, router
            # flips, v1 drains — zero drops, zero recompiles/misses in
            # the serving window (the warm phase compiles OUTSIDE the
            # counted window by design: warm_start entries install
            # without touching the miss counters)
            pred2 = _serving_predictor(kind, seed=2)
            before = _exec_counters()
            stop = threading.Event()
            swap_err = []
            n_ok = [0]

            def client_loop():
                i = 0
                while not stop.is_set():
                    try:
                        mgr.infer(kind, requests[i % 64], timeout=600)
                        n_ok[0] += 1
                    except Exception as e:
                        swap_err.append(repr(e)[:120])
                        return
                    i += 1
            ts = [threading.Thread(target=client_loop)
                  for _ in range(GEN_CLIENTS)]
            for t in ts:
                t.start()
            time.sleep(0.2)
            swap_info = mgr.swap(kind, "2", predictor=pred2,
                                 buckets=BUCKETS, max_delay_ms=4.0,
                                 max_queue_rows=8192)
            time.sleep(0.2)
            stop.set()
            for t in ts:
                t.join()
            after = _exec_counters()
            res["swap"] = {
                "served_during_swap": n_ok[0],
                "dropped": len(swap_err),
                "swap_ms": swap_info["ms"],
                "drained": swap_info["drained"],
                "recompiles_delta": {
                    k.split(".", 1)[1]: after[k] - before[k]
                    for k in after},
            }
        mgr.close()
        out[kind] = res

    # headline for tools/bench_compare.py: sustained batched QPS on the
    # mnist predictor (the ≥4×-vs-sequential acceptance metric)
    out["batched_qps"] = out["mnist"]["batched_qps"]
    out["speedup_vs_sequential"] = out["mnist"]["speedup"]
    out["serving_phase_p99_ms"] = out["mnist"].get("phase_p99_ms")
    # informational capacity keys (bench_compare carries headroom_frac
    # without gating on it)
    for k in ("utilization", "headroom_frac", "binding_phase",
              "predicted_max_qps"):
        if out["mnist"].get(k) is not None:
            out[k] = out["mnist"][k]
    # correctness-in-window headline: what the canary cost
    # (informational) and whether any probe mismatched (a secondary
    # gate — 0 on a healthy build)
    from paddle_tpu.observability import canary as _canary
    cp = _canary.prober(create=False)
    out["canary_overhead_frac"] = round(_canary.overhead_frac(), 6)
    out["canary_failures"] = (sum(
        s["failures"] for s in cp.streaks().values()) if cp else 0)

    # -- int8 serving arm (fused-dequant quantized matmul) ----------------
    # same two models through the quantize_int8 calibration pipeline:
    # accuracy parity (argmax agreement vs the f32 predictor — the
    # declared bar below), batched QPS, and the zero-steady-state-
    # recompile pin.  quant_accuracy_delta gates as a secondary in
    # tools/bench_compare.py (lower-better: a parity collapse is a
    # regression even when QPS holds)
    from paddle_tpu.kernels import quant as _quant
    INT8_PARITY_BAR = 0.05
    int8_res = {}
    worst = 0.0
    for kind in ("mnist", "transformer"):
        pred_f = _serving_predictor(kind)
        pred_q = _serving_predictor(kind, int8=True)
        reqs = [_serving_request(kind, rng) for _ in range(64)]
        agree, total = 0, 0
        for feed in reqs:
            a = np.asarray(pred_f.run(feed)[0])
            b = np.asarray(pred_q.run(feed)[0])
            ia = a.reshape(-1, a.shape[-1]).argmax(-1)
            ib = b.reshape(-1, b.shape[-1]).argmax(-1)
            agree += int((ia == ib).sum())
            total += ia.size
        delta = 1.0 - agree / max(total, 1)
        worst = max(worst, delta)
        mgr8 = ModelManager()
        mgr8.load(f"{kind}_int8", "1", predictor=pred_q, warm=True,
                  buckets=BUCKETS, activate=True, max_delay_ms=4.0,
                  max_queue_rows=8192)
        mgr8.infer(f"{kind}_int8", reqs[0], timeout=600)
        before = _exec_counters()
        qps8, p508, p998, err8 = _serving_load(
            lambda feed, _k=kind: mgr8.submit(f"{_k}_int8", feed),
            [reqs[i % 64] for i in range(256)], GEN_CLIENTS,
            window=WINDOW)
        after = _exec_counters()
        rec8 = {k.split(".", 1)[1]: after[k] - before[k] for k in after}
        mgr8.close()
        assert all(v == 0 for v in rec8.values()), rec8
        int8_res[kind] = {
            "batched_qps": qps8, "p50_ms": p508, "p99_ms": p998,
            "argmax_delta": round(delta, 4), "dropped": len(err8),
            "recompiles_in_window": rec8,
        }
    # fallback counters over the whole arm: how many quantized matmuls
    # launched vs fell back (quant.* — the /quantz payload's counters)
    int8_res["quant_counters"] = dict(_quant._COUNTERS)
    assert worst <= INT8_PARITY_BAR, (worst, INT8_PARITY_BAR)
    out["int8"] = int8_res
    out["quant_accuracy_delta"] = round(worst, 4)
    out["quant_parity_bar"] = INT8_PARITY_BAR
    return out


def bench_decode():
    """Autoregressive decode plane (paddle_tpu/decode) vs the naive
    re-prefill-every-token baseline.

    Model: a tiny decoder-only TransformerLM (serving-shaped geometry,
    tier-1 speed).  Two ways to generate the same greedy tokens:

    - ``reprefill``: the pre-decode-plane shape — every generated token
      re-runs the FULL causal forward over the whole prefix (padded to
      the prefill bucket ladder so the baseline also never recompiles),
      one request at a time.  This is what PR-8-style one-shot serving
      would do for generative traffic; per-token cost grows with the
      prefix.
    - ``continuous``: the DecodeEngine — paged KV cache, token-level
      continuous batching over ``max_slots`` slots, split
      prefill/decode dispatch — offered all requests at once
      (saturation: more requests than slots, so the batch runs full and
      join/leave churns at token granularity).

    Reported: tokens/s for both, per-token p99 (client-perceived
    inter-token interval for the engine; measured per-token wall for
    the baseline), the engine's zero-recompile pin over the serving
    window, and a greedy-parity artifact (engine tokens vs re-prefill
    argmax on shared prompts) — the acceptance's exactness evidence
    riding the same artifact as its speedup.  Off-TPU the whole config
    is CPU-measured policy evidence and labels itself ``analysis:
    true`` (the deepfm_fused precedent); the on-chip capture is ROADMAP
    item 1's ``decode`` row."""
    from paddle_tpu.core import flags as _flags

    # token-level tail anatomy (TTFT/TBT histograms, goodput, phases)
    # plus capacity attribution ride the saturation window — host-side
    # stamps, no device syncs (finally-restored like bench_serving)
    # golden canary rides the continuous window too (bench_serving
    # precedent): a recorded greedy completion replayed through the
    # real engine submit path, costed as canary_overhead_frac and
    # gated as canary_failures in tools/bench_compare.py
    # memory anatomy rides the same window: the engine registers its KV
    # block pool on the ledger, so the artifact carries the measured
    # bytes-per-token cost and the reconciliation residual
    _flags.set_flags({"phase_attribution": True,
                      "capacity_attribution": True,
                      "canary_probe": True,
                      "canary_interval_s": 0.25,
                      "memory_attribution": True})
    try:
        return _bench_decode_inner()
    finally:
        _flags.set_flags({"phase_attribution": False,
                          "capacity_attribution": False,
                          "canary_probe": False,
                          "canary_interval_s": 5.0,
                          "memory_attribution": False})
        from paddle_tpu.observability import canary as _canary
        from paddle_tpu.observability import capacity as _capacity
        from paddle_tpu.observability import memory as _memory
        _canary.reset()
        _capacity.reset()
        _memory.reset()


def _bench_decode_inner():
    import jax

    from paddle_tpu.core.executor import Executor
    from paddle_tpu.decode import (DecodeEngine, LMConfig, SamplingParams,
                                   TransformerLM)
    from paddle_tpu.serving import BucketLadder

    cfg = LMConfig(vocab=256, d_model=64, n_head=4, d_ffn=128, n_layer=2,
                   max_seq_len=128)
    lm = TransformerLM(cfg)
    params = lm.init_params(seed=7)
    BUCKETS = (32, 64, 128)
    SLOTS = 16
    rng = np.random.RandomState(0)
    # generative traffic shape: prompts 8..64 tokens, outputs 16..32 —
    # long enough that the baseline's per-token full re-forward over
    # the growing prefix pays its quadratic bill
    reqs = [(rng.randint(0, cfg.vocab, int(rng.randint(8, 64))).astype(
        "int32"), int(rng.randint(16, 33))) for _ in range(36)]
    total_tokens = sum(m for _, m in reqs)

    # -- re-prefill baseline ------------------------------------------------
    exe = Executor(training=False)
    plist = lm.param_list(params)

    ladder = BucketLadder(BUCKETS)

    def full_bucket(prefix):
        return ladder.snap(len(prefix))

    def build_full():
        def fn(feed, state, const):
            logits = lm.full_logits(const, feed[0], feed[1])
            return [logits], []
        return fn

    def reprefill_one(prompt, max_new):
        toks = list(int(t) for t in prompt)
        lats = []
        for _ in range(max_new):
            t0 = time.perf_counter()
            b = full_bucket(toks)
            padded = np.zeros((1, b), np.int32)
            padded[0, :len(toks)] = toks
            (lg,), _ = exe.run_callable(
                f"bench/reprefill/{b}", build_full,
                [padded, np.asarray([len(toks)], np.int32)], [], plist)
            last = np.asarray(lg)[0, len(toks) - 1]
            toks.append(int(last.argmax()))
            lats.append((time.perf_counter() - t0) * 1e3)
        return toks[len(prompt):], lats

    # warm the baseline ladder outside the measured window (prompt of
    # b-2 tokens snaps to bucket b)
    for b in BUCKETS:
        reprefill_one(np.zeros(b - 2, np.int32), 1)
    t0 = time.perf_counter()
    base_tokens = {}
    base_lats = []
    for i, (p, m) in enumerate(reqs):
        toks, lats = reprefill_one(p, m)
        base_tokens[i] = toks
        base_lats.extend(lats)
    base_wall = time.perf_counter() - t0
    base_tps = total_tokens / base_wall

    # -- continuous decode batching ----------------------------------------
    eng = DecodeEngine(lm, params, name="bench", max_slots=SLOTS,
                       block_tokens=16, prefill_buckets=BUCKETS,
                       max_queue=len(reqs) + 4,
                       # off-TPU the Pallas kernel runs in interpret
                       # mode — honest CPU policy numbers use the XLA
                       # gather path (the counted-fallback twin); on
                       # TPU the kernel path is the measured one
                       attn_impl=("xla" if jax.default_backend() != "tpu"
                                  else None))
    # warm: one request per prefill bucket + the decode step
    for b in BUCKETS:
        eng.generate(np.zeros(b - 2, np.int32), max_new_tokens=2)

    # golden canary in-window: record one greedy completion against the
    # warmed engine, then let the prober replay it through the REAL
    # submit path concurrently with the continuous window (probes are
    # __canary__-tenant streams, excluded from user metering)
    from paddle_tpu.observability import canary as _canary
    g_prompt, g_new = reqs[0][0], 8
    g_toks = eng.generate(g_prompt, max_new_tokens=g_new)["tokens"]
    cp = _canary.prober()
    if cp is not None:
        cp.goldens.models["bench"] = {"rtol": None, "cases": [{
            "feeds": {"prompt": np.asarray(g_prompt, np.int32),
                      "max_new_tokens": np.asarray(g_new, np.int32)},
            "expect": [("tokens", np.asarray(g_toks, np.int32))]}]}

    def _canary_decode(feeds, tenant, _eng=eng):
        h = _eng.submit(
            np.asarray(feeds["prompt"], np.int32),
            SamplingParams(max_new_tokens=int(
                np.asarray(feeds["max_new_tokens"]))),
            tenant=tenant)
        return [("tokens",
                 np.asarray(h.result(timeout=600)["tokens"], np.int32))]

    _canary.register_target("bench/decode", "bench", _canary_decode)
    _canary.maybe_start_from_flags()

    before = _exec_counters()
    t0 = time.perf_counter()
    handles = [eng.submit(p, SamplingParams(max_new_tokens=m))
               for p, m in reqs]
    results = [h.result(timeout=600) for h in handles]
    cont_wall = time.perf_counter() - t0
    after = _exec_counters()
    cont_tps = total_tokens / cont_wall
    token_p99 = eng.stats.token_ms.percentile(0.99)
    token_p50 = eng.stats.token_ms.percentile(0.50)
    lat = eng.stats.lat
    ttft_p99 = lat.ttft_ms.percentile(0.99) if lat else None
    ttft_p50 = lat.ttft_ms.percentile(0.50) if lat else None
    tbt_p99 = lat.tbt_ms.percentile(0.99) if lat else None
    goodput = lat.goodput() if lat else None
    phase_p99 = lat.phases.phase_p99_ms() if lat else None
    # capacity snapshot BEFORE close() (close unregisters the tracker)
    cap = eng.stats.capacity()
    cap_snap = cap.snapshot() if cap is not None else {}
    # memory ledger BEFORE close() (close unregisters the KV pool):
    # measured per-token KV cost + the reconciliation residual
    from paddle_tpu.observability import memory as _memory
    kv_bytes_per_token = round(
        eng._block_bytes / max(eng.cache.block_tokens, 1), 3)
    led = _memory.ledger(set_gauges=False)
    unattributed = sum(
        int(d.get("unattributed_bytes") or 0)
        for d in (led.get("devices") or {}).values())

    # greedy parity: continuous tokens == re-prefill argmax tokens
    mismatches = sum(1 for i, r in enumerate(results)
                    if r["tokens"] != base_tokens[i])
    # retire the canary target BEFORE close (a probe against a closed
    # engine would read as a correctness failure)
    _canary.unregister_target("bench/decode")
    canary_overhead = round(_canary.overhead_frac(), 6)
    canary_failures = (sum(s["failures"] for s in cp.streaks().values())
                       if cp else 0)
    eng.close()

    base_lats.sort()
    out = {
        "note": "CPU in-process: isolates the cache/batching policy; "
                "on-chip capture pending tunnel (ROADMAP item 1 "
                "'decode' row)",
        "model": cfg.to_dict(),
        "requests": len(reqs), "total_tokens": total_tokens,
        "slots": SLOTS, "prefill_buckets": list(BUCKETS),
        "reprefill_tokens_per_sec": round(base_tps, 1),
        "reprefill_token_p50_ms": round(
            base_lats[len(base_lats) // 2], 3),
        "reprefill_token_p99_ms": round(
            base_lats[min(int(0.99 * len(base_lats)),
                          len(base_lats) - 1)], 3),
        "decode_tokens_per_sec": round(cont_tps, 1),
        "decode_token_p50_ms": token_p50,
        "decode_token_p99_ms": token_p99,
        # token-level tail SLOs (gated like throughput by
        # tools/bench_compare.py: decode_ttft_ms_p99 is lower-better)
        "decode_ttft_ms_p50": ttft_p50,
        "decode_ttft_ms_p99": ttft_p99,
        "decode_tbt_ms_p99": tbt_p99,
        "goodput": goodput,
        "phase_p99_ms": phase_p99,
        # saturation anatomy over the continuous window (informational
        # in bench_compare: headroom_frac never gates)
        "utilization": cap_snap.get("utilization"),
        "headroom_frac": cap_snap.get("headroom_frac"),
        "binding_phase": cap_snap.get("binding_phase"),
        "predicted_max_qps": cap_snap.get("predicted_max_qps"),
        # correctness-in-window: probe cost (informational) + mismatch
        # count (secondary gate, 0 on a healthy build)
        "canary_overhead_frac": canary_overhead,
        "canary_failures": canary_failures,
        # memory anatomy over the same window (informational in
        # bench_compare; kv_bytes_per_token is lower-better)
        "kv_bytes_per_token": kv_bytes_per_token,
        "unattributed_bytes": unattributed,
        "speedup_vs_reprefill": round(cont_tps / max(base_tps, 1e-9), 2),
        "parity": {"greedy_mismatched_requests": mismatches,
                   "requests_compared": len(reqs)},
        "recompiles_in_window": {
            k.split(".", 1)[1]: after[k] - before[k] for k in after},
    }
    assert mismatches == 0, out["parity"]
    if jax.default_backend() != "tpu":
        out["analysis"] = True
    return out


def bench_decode_prefix():
    """Prefix caching + overcommit (the refcounted block lifecycle,
    ``FLAGS_decode_prefix_cache`` / ``FLAGS_decode_overcommit``) vs the
    single-owner baseline, two legs:

    - **shared prefix**: 64 requests sharing an 87% system prompt
      (416 of 480 tokens), offered to a prefix-on engine vs the same
      engine with the flag off.  The prefix-on run prefills the shared
      blocks ONCE (request 0), every later admission reuses them and
      prefills only its 64-token suffix — ``saved_prefill_tokens`` must
      equal the analytic count EXACTLY (63 x 416) and the greedy tokens
      must match the prefix-off run per request.  Headline:
      ``decode_tokens_per_sec`` over the offered window plus mean TTFT
      both ways; ``prefix_hit_rate`` gates as a secondary in
      tools/bench_compare.py (a hit rate collapse is a regression even
      if throughput holds).  Zero recompiles in both measured windows
      (suffix lengths ride the resume bucket ladder).
    - **overcommit**: a block pool sized for HALF the offered streams'
      full reservation.  The reservation baseline can only run as many
      slots as full reservations fit; overcommit admits on the prompt
      footprint, grows block-by-block, and preempts the newest stream
      under pressure (token-exact re-prefill resume).  Measured: slot
      occupancy over the loaded window (queue nonempty) both ways —
      the overcommit run must hold >= 0.9 with >= 1 real preemption —
      completion of ALL streams, and zero token divergence between
      preempted-and-resumed streams and the reservation run.

    Off-TPU both legs are CPU policy evidence (``analysis: true``, the
    bench_decode precedent)."""
    from paddle_tpu.core import flags as _flags

    # token-level anatomy (TTFT histograms + goodput lane counters —
    # the occupancy evidence) rides both legs, finally-restored; memory
    # attribution rides too so the artifact carries measured KV cost
    _flags.set_flags({"phase_attribution": True,
                      "memory_attribution": True})
    try:
        return _bench_decode_prefix_inner()
    finally:
        _flags.set_flags({"phase_attribution": False,
                          "memory_attribution": False})
        from paddle_tpu.observability import memory as _memory
        _memory.reset()


def _bench_decode_prefix_inner():
    import threading

    import jax

    from paddle_tpu.decode import (DecodeEngine, LMConfig, SamplingParams,
                                   TransformerLM)

    impl = "xla" if jax.default_backend() != "tpu" else None

    # -- leg 1: shared-prefix prefill dedup --------------------------------
    # heavier geometry than bench_decode: the full prefill runs 512
    # dense rows where the suffix path runs 64, so model cost widens
    # the gap the cache exploits.  max_new=1: the first token samples
    # inside the prefill dispatch, so the window isolates exactly what
    # the prefix cache accelerates (decode-step throughput is
    # bench_decode's row; the overcommit leg below runs
    # decode-step-heavy traffic on a smaller model)
    cfg = LMConfig(vocab=256, d_model=192, n_head=4, d_ffn=768, n_layer=3,
                   max_seq_len=512)
    lm = TransformerLM(cfg)
    params = lm.init_params(seed=7)
    BS, SLOTS, N, MAX_NEW = 32, 16, 64, 1
    SHARED, UNIQ = 416, 64           # 13 shared blocks, 87% of the prompt
    BUCKETS = (512,)
    rng = np.random.RandomState(3)
    shared = rng.randint(0, cfg.vocab, SHARED).astype("int32")
    prompts = [np.concatenate([shared,
                               rng.randint(0, cfg.vocab, UNIQ).astype(
                                   "int32")]) for _ in range(N)]

    def run_shared(prefix_on):
        eng = DecodeEngine(lm, params,
                           name="bpx_on" if prefix_on else "bpx_off",
                           max_slots=SLOTS, block_tokens=BS,
                           prefill_buckets=BUCKETS, max_queue=N + 4,
                           attn_impl=impl, prefix_cache=prefix_on,
                           overcommit=False)
        # warm out-of-window: the full-prefill bucket + the decode step,
        # and (prefix on) the suffix executable — a second warm prompt
        # sharing the first one's block prefix dispatches prefill_sfx
        # on the same resume bucket the measured suffixes snap to
        w1 = np.full(510, 1, np.int32)
        eng.generate(w1, max_new_tokens=2)
        if prefix_on:
            w2 = w1.copy()
            w2[448:] = 2             # diverge at block 14: 62-token suffix
            eng.generate(w2, max_new_tokens=2)
        ps = eng._pstats
        saved0 = ps.saved_prefill_tokens.value if ps else 0
        hits0 = ps.prefix_hits.value if ps else 0
        lk0 = ps.prefix_lookups.value if ps else 0
        before = _exec_counters()
        ttfts = [0.0] * N
        threads = []

        def first_tok(i, h, t0):
            h.next_token(timeout=600)
            ttfts[i] = (time.perf_counter() - t0) * 1e3

        t_start = time.perf_counter()
        # request 0 goes first and we WAIT for its first token: its
        # prefill registers the shared blocks, so every later request
        # hits them — the analytic saved-token count stays exact.  The
        # prefix-off run follows the same staged protocol for fairness.
        h0 = eng.submit(prompts[0], SamplingParams(max_new_tokens=MAX_NEW))
        h0.next_token(timeout=600)
        ttfts[0] = (time.perf_counter() - t_start) * 1e3
        handles = [h0]
        for i in range(1, N):
            t0 = time.perf_counter()
            h = eng.submit(prompts[i],
                           SamplingParams(max_new_tokens=MAX_NEW))
            th = threading.Thread(target=first_tok, args=(i, h, t0))
            th.start()
            threads.append(th)
            handles.append(h)
        results = [h.result(timeout=600) for h in handles]
        for th in threads:
            th.join()
        wall = time.perf_counter() - t_start
        after = _exec_counters()
        z = eng.decodez()
        leaked = eng.cache.allocator.leaked(
            eng.prefix.parked_blocks if eng.prefix else 0)
        out = {
            "tps": (N * MAX_NEW) / wall,
            "ttft_mean_ms": sum(ttfts) / N,
            "tokens": [r["tokens"] for r in results],
            "saved": (ps.saved_prefill_tokens.value - saved0) if ps else 0,
            "hits": (ps.prefix_hits.value - hits0) if ps else 0,
            "lookups": (ps.prefix_lookups.value - lk0) if ps else 0,
            "leaked": leaked,
            "prefix_card": z.get("prefix_cache"),
            "recompiles": {k.split(".", 1)[1]: after[k] - before[k]
                           for k in after},
        }
        # memory ledger BEFORE close() (close unregisters the KV pool)
        from paddle_tpu.observability import memory as _memory
        out["kv_bytes_per_token"] = round(
            eng._block_bytes / max(eng.cache.block_tokens, 1), 3)
        led = _memory.ledger(set_gauges=False)
        out["unattributed_bytes"] = sum(
            int(d.get("unattributed_bytes") or 0)
            for d in (led.get("devices") or {}).values())
        eng.close()
        return out

    off = run_shared(False)
    on = run_shared(True)
    assert on["tokens"] == off["tokens"], \
        "prefix-on greedy tokens diverged from prefix-off"
    expect_saved = (N - 1) * SHARED
    assert on["saved"] == expect_saved, (on["saved"], expect_saved)
    assert on["leaked"] == 0 and off["leaked"] == 0, (on["leaked"],
                                                      off["leaked"])
    for leg in (off, on):
        assert all(v == 0 for v in leg["recompiles"].values()), \
            leg["recompiles"]
    hit_rate = on["hits"] / max(on["lookups"], 1)

    # -- leg 2: overcommit + preemption under a half-sized pool ------------
    # smaller model (decode steps dominate this leg, the policy under
    # test is block accounting, not matmul throughput)
    cfg2 = LMConfig(vocab=256, d_model=128, n_head=4, d_ffn=256,
                    n_layer=2, max_seq_len=512)
    lm2 = TransformerLM(cfg2)
    params2 = lm2.init_params(seed=11)
    BS2, SLOTS2, N2, M2, P2 = 16, 16, 24, 112, 16
    FULL = (P2 + M2 + BS2 - 1) // BS2          # reservation: 8 blocks
    POOL = 1 + (N2 // 2) * FULL                # half the offered streams
    BUCKETS2 = (16, 32, 64, 128)
    prompts2 = [rng.randint(0, cfg2.vocab, P2).astype("int32")
                for _ in range(N2)]

    def run_overcommit(overcommit_on):
        eng = DecodeEngine(lm2, params2,
                           name="boc_on" if overcommit_on else "boc_off",
                           max_slots=SLOTS2, block_tokens=BS2,
                           num_blocks=POOL, prefill_buckets=BUCKETS2,
                           max_queue=N2 + 4, attn_impl=impl,
                           prefix_cache=False, overcommit=overcommit_on)
        # warm every prefill bucket: preemption re-prefill lengths
        # (P2..P2+M2-1) snap onto the same ladder, so the churny
        # window stays recompile-free too
        for b in BUCKETS2:
            eng.generate(np.full(b - 2, 1, np.int32), max_new_tokens=2)
        lat = eng.stats.lat
        before = _exec_counters()
        live0, pad0 = lat.live_slot_steps.value, lat.pad_slot_steps.value
        loaded = {"live": live0, "pad": pad0}
        done = threading.Event()

        def monitor():
            # loaded-window occupancy: lane counters at the LAST
            # instant the queue was nonempty (the drain tail, where
            # slots empty because no work is left, must not read as
            # an occupancy loss)
            while not done.is_set():
                if eng.stats.queue.value > 0:
                    loaded["live"] = lat.live_slot_steps.value
                    loaded["pad"] = lat.pad_slot_steps.value
                time.sleep(0.002)

        mon = threading.Thread(target=monitor)
        mon.start()
        t0 = time.perf_counter()
        handles = [eng.submit(p, SamplingParams(max_new_tokens=M2))
                   for p in prompts2]
        results = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
        done.set()
        mon.join()
        after = _exec_counters()
        lw, pw = loaded["live"] - live0, loaded["pad"] - pad0
        ps = eng._pstats
        leaked = eng.cache.allocator.leaked()
        out = {
            "tps": sum(r["n_tokens"] for r in results) / wall,
            "occupancy": lw / max(lw + pw, 1),
            "tokens": [r["tokens"] for r in results],
            "completed": sum(1 for r in results
                             if r["finish"] == "length"),
            "preempts": ps.preempts.value if ps else 0,
            "resumes": ps.preempt_resumes.value if ps else 0,
            "reprefill_tokens": ps.reprefill_tokens.value if ps else 0,
            "leaked": leaked,
            "recompiles": {k.split(".", 1)[1]: after[k] - before[k]
                           for k in after},
        }
        eng.close()
        return out

    oc_off = run_overcommit(False)
    oc_on = run_overcommit(True)
    # token-exactness across preemption: greedy decode is per-stream
    # deterministic, so the reservation run IS the uninterrupted truth
    divergent = sum(1 for a, b in zip(oc_on["tokens"], oc_off["tokens"])
                    if a != b)
    assert divergent == 0, f"{divergent} preempted streams diverged"
    assert oc_on["completed"] == N2 and oc_off["completed"] == N2
    assert oc_on["preempts"] >= 1, "overcommit leg saw no preemption"
    assert oc_on["leaked"] == 0 and oc_off["leaked"] == 0
    assert all(v == 0 for v in oc_on["recompiles"].values()), \
        oc_on["recompiles"]

    out = {
        "note": "CPU in-process: isolates the block-lifecycle policy "
                "(prefix dedup, COW, preemption); on-chip capture "
                "pending tunnel (ROADMAP item 1 'decode' row)",
        "model": cfg.to_dict(),
        "overcommit_model": cfg2.to_dict(),
        "requests": N, "shared_prefix_tokens": SHARED,
        "unique_tail_tokens": UNIQ, "max_new": MAX_NEW,
        "slots": SLOTS, "block_tokens": BS,
        # headline (gated by tools/bench_compare.py METRIC_KEYS)
        "decode_tokens_per_sec": round(on["tps"], 1),
        "prefix_off_tokens_per_sec": round(off["tps"], 1),
        "prefix_speedup": round(on["tps"] / max(off["tps"], 1e-9), 2),
        "ttft_mean_ms_prefix_on": round(on["ttft_mean_ms"], 2),
        "ttft_mean_ms_prefix_off": round(off["ttft_mean_ms"], 2),
        "ttft_speedup": round(off["ttft_mean_ms"] /
                              max(on["ttft_mean_ms"], 1e-9), 2),
        # secondary gate (bench_compare SECONDARY_GATE_KEYS): a hit
        # rate collapse is a regression even when throughput holds
        "prefix_hit_rate": round(hit_rate, 4),
        # memory anatomy over the prefix-on window (informational in
        # bench_compare; kv_bytes_per_token is lower-better)
        "kv_bytes_per_token": on["kv_bytes_per_token"],
        "unattributed_bytes": on["unattributed_bytes"],
        "saved_prefill_tokens": on["saved"],
        "saved_prefill_tokens_expected": expect_saved,
        "prefix_cache": on["prefix_card"],
        "recompiles_in_window": on["recompiles"],
        "overcommit": {
            "offered_streams": N2, "slots": SLOTS2,
            "pool_blocks": POOL, "full_blocks_per_stream": FULL,
            "overcommit_tokens_per_sec": round(oc_on["tps"], 1),
            "reservation_tokens_per_sec": round(oc_off["tps"], 1),
            "occupancy_overcommit": round(oc_on["occupancy"], 4),
            "occupancy_reservation": round(oc_off["occupancy"], 4),
            "preempts": oc_on["preempts"],
            "resumes": oc_on["resumes"],
            "reprefill_tokens": oc_on["reprefill_tokens"],
            "divergent_streams": divergent,
            "completed_streams": oc_on["completed"],
        },
    }
    assert out["prefix_speedup"] >= 2.0, out["prefix_speedup"]
    assert out["ttft_speedup"] >= 2.0, out["ttft_speedup"]
    assert oc_on["occupancy"] >= 0.9, oc_on["occupancy"]
    if jax.default_backend() != "tpu":
        out["analysis"] = True
    return out


def bench_decode_kv_int8():
    """Quantized KV residency (``FLAGS_decode_kv_dtype=int8``) vs the
    fp32 cache at the SAME pool byte budget, under overcommit.

    The int8 cache stores paged blocks as int8 codes plus a
    per-block-per-head scale pool, cutting bytes-per-block ~4x
    (codes are a quarter of f32; the scale rows are noise), so the same
    HBM budget holds ~4x the blocks and overcommit admits far more
    resident sequences before preempting.  Two legs, identical offered
    load and identical pool BYTES (the int8 engine gets the block count
    that budget buys):

    - measured: decode tokens/s, mean resident sequences per decode
      step over the run (live-lane counters), kv_bytes_per_token
      (dtype-aware: engine block bytes include the scale pools), and
      greedy divergence vs the fp32 run — the first token must match
      (prefill attention runs on fresh f32 K/V either way) and the
      per-stream matched-prefix fraction is reported (quantization
      noise compounds over a greedy chain; the BOUND is the exact
      first token + the reported tail).
    - pinned: byte ratio <= 0.55, resident-sequence gain >= 1.8, all
      streams complete both ways, zero steady-state recompiles, zero
      leaked blocks.

    Off-TPU this is CPU policy evidence (``analysis: true``, the
    bench_decode precedent — the paged kernel's VMEM dequant is the
    on-chip capture, ROADMAP item 1 'decode_kv_int8' row)."""
    from paddle_tpu.core import flags as _flags

    _flags.set_flags({"phase_attribution": True,
                      "memory_attribution": True})
    try:
        return _bench_decode_kv_int8_inner()
    finally:
        _flags.set_flags({"phase_attribution": False,
                          "memory_attribution": False})
        from paddle_tpu.observability import memory as _memory
        _memory.reset()


def _bench_decode_kv_int8_inner():
    import threading

    import jax

    from paddle_tpu.decode import (DecodeEngine, LMConfig, SamplingParams,
                                   TransformerLM)
    from paddle_tpu.decode.cache import PagedKVCache

    impl = "xla" if jax.default_backend() != "tpu" else None
    cfg = LMConfig(vocab=256, d_model=128, n_head=4, d_ffn=256, n_layer=2,
                   max_seq_len=256)
    lm = TransformerLM(cfg)
    params = lm.init_params(seed=5)
    BS, SLOTS, N, M, P = 16, 16, 24, 48, 16
    FULL = (P + M + BS - 1) // BS              # 4 blocks per full stream
    POOL_F32 = 1 + 4 * FULL                    # fp32: ~4 resident streams
    BUCKETS = (16, 32, 64)
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, cfg.vocab, P).astype("int32")
               for _ in range(N)]

    def run(dtype, num_blocks):
        eng = DecodeEngine(lm, params, name=f"bkv_{dtype}",
                           max_slots=SLOTS, block_tokens=BS,
                           num_blocks=num_blocks,
                           prefill_buckets=BUCKETS, max_queue=N + 4,
                           attn_impl=impl, prefix_cache=False,
                           overcommit=True, cache_dtype=dtype)
        # warm every prefill bucket (preemption re-prefill lengths
        # P..P+M-1 snap onto the same ladder) plus the decode step
        for b in BUCKETS:
            eng.generate(np.full(b - 2, 1, np.int32), max_new_tokens=2)
        lat = eng.stats.lat
        before = _exec_counters()
        live0 = lat.live_slot_steps.value
        steps0 = eng.stats.steps.value
        t0 = time.perf_counter()
        handles = [eng.submit(p, SamplingParams(max_new_tokens=M))
                   for p in prompts]
        results = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
        after = _exec_counters()
        steps = eng.stats.steps.value - steps0
        out = {
            "tps": sum(r["n_tokens"] for r in results) / wall,
            "tokens": [r["tokens"] for r in results],
            "completed": sum(1 for r in results
                             if r["finish"] == "length"),
            # mean live slots per decode step: the residency the pool
            # byte budget actually sustained over the run
            "resident_mean": ((lat.live_slot_steps.value - live0)
                              / max(steps, 1)),
            "kv_bytes_per_token": round(eng._block_bytes / BS, 3),
            "pool_bytes": eng.cache.nbytes,
            "num_blocks": eng.cache.num_blocks,
            "preempts": eng._pstats.preempts.value,
            "leaked": eng.cache.allocator.leaked(),
            "recompiles": {k.split(".", 1)[1]: after[k] - before[k]
                           for k in after},
        }
        eng.close()
        return out

    f32 = run("float32", POOL_F32)
    # same byte budget: how many int8 blocks (codes + scale rows) the
    # fp32 pool's bytes buy
    probe = PagedKVCache(cfg.n_layer, cfg.n_head, cfg.head_dim, 2, BS,
                         dtype="int8")
    POOL_I8 = max(int(f32["pool_bytes"] // (probe.nbytes // 2)), 2)
    q = run("int8", POOL_I8)

    assert q["pool_bytes"] <= f32["pool_bytes"], (q["pool_bytes"],
                                                  f32["pool_bytes"])
    assert f32["completed"] == N and q["completed"] == N
    assert f32["leaked"] == 0 and q["leaked"] == 0
    for leg in (f32, q):
        assert all(v == 0 for v in leg["recompiles"].values()), \
            leg["recompiles"]
    byte_ratio = q["kv_bytes_per_token"] / f32["kv_bytes_per_token"]
    resident_gain = q["resident_mean"] / max(f32["resident_mean"], 1e-9)
    # greedy divergence vs the fp32 run (the uninterrupted truth:
    # preemption resume is token-exact).  The first token samples
    # inside prefill on fresh f32 K/V, so it is exact by construction;
    # later tokens read the quantized cache and may drift
    matched = []
    first_mismatch = 0
    for a, b in zip(q["tokens"], f32["tokens"]):
        if a[:1] != b[:1]:
            first_mismatch += 1
        m = 0
        for x, y in zip(a, b):
            if x != y:
                break
            m += 1
        matched.append(m / max(len(b), 1))
    assert first_mismatch == 0, \
        f"{first_mismatch} streams diverged at the (exact) first token"

    out = {
        "note": "CPU in-process: isolates the quantized-cache residency "
                "policy; on-chip capture pending tunnel (ROADMAP item 1 "
                "'decode_kv_int8' row)",
        "model": cfg.to_dict(),
        "requests": N, "prompt_tokens": P, "max_new": M,
        "slots": SLOTS, "block_tokens": BS,
        "pool_bytes": f32["pool_bytes"],
        "blocks_fp32": f32["num_blocks"],
        "blocks_int8": q["num_blocks"],
        # headline
        "decode_tokens_per_sec": round(q["tps"], 1),
        "fp32_tokens_per_sec": round(f32["tps"], 1),
        # lower-better + informational in bench_compare
        "kv_bytes_per_token": q["kv_bytes_per_token"],
        "kv_bytes_per_token_fp32": f32["kv_bytes_per_token"],
        "kv_byte_ratio": round(byte_ratio, 4),
        "resident_mean_int8": round(q["resident_mean"], 2),
        "resident_mean_fp32": round(f32["resident_mean"], 2),
        "resident_gain": round(resident_gain, 2),
        "preempts_fp32": f32["preempts"],
        "preempts_int8": q["preempts"],
        "greedy_divergence": {
            "first_token_mismatches": first_mismatch,
            "matched_prefix_frac_mean": round(
                sum(matched) / max(len(matched), 1), 4),
            "matched_prefix_frac_min": round(min(matched), 4),
            "fully_matched_streams": sum(1 for m in matched if m >= 1.0),
        },
        "recompiles_in_window": q["recompiles"],
    }
    assert byte_ratio <= 0.55, byte_ratio
    assert resident_gain >= 1.8, resident_gain
    if jax.default_backend() != "tpu":
        out["analysis"] = True
    return out


A100_RESNET50_IMG_S = 2500.0
A100_TRANSFORMER_TOK_S = 50000.0


def _compile_cache_child_main():
    """Grandchild for bench_compile_cache: one fresh process builds the
    LeNet train program and reports its time-to-first-step (startup →
    first trained batch readback) plus the persistent-cache counters
    that explain it.  FLAGS_compile_cache_dir comes in via env."""
    import os
    import sys

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as fluid  # noqa: F401
    from paddle_tpu.core.executor import Executor, Scope
    from paddle_tpu.models import mnist

    B = 64
    prog, startup, (feeds, loss, acc) = _fresh(lambda: mnist.build())
    rng = np.random.RandomState(0)
    feed = {"pixel": rng.randn(B, 1, 28, 28).astype("float32"),
            "label": rng.randint(0, 10, (B, 1)).astype("int64")}
    scope = Scope()
    exe = Executor()
    exe.run(startup, scope=scope)
    t0 = time.perf_counter()
    if os.environ.get("PADDLE_TPU_BENCH_CC_WARMSTART"):
        # the elastic-rejoin shape: hydrate explicitly, then step
        exe.warm_start(prog, feed_specs=feed, fetch_list=[loss.name],
                       scope=scope)
    (lv,) = exe.run(prog, feed=feed, fetch_list=[loss.name], scope=scope)
    float(np.asarray(lv))
    ttfs = time.perf_counter() - t0
    from paddle_tpu import observability as obs
    c = obs.stats.default_registry().to_dict()
    print("CCCHILD=" + json.dumps({
        "ttfs_s": round(ttfs, 4),
        "persistent_hits": c.get("executor.persistent_hits", 0),
        "persistent_misses": c.get("executor.persistent_misses", 0)}),
        flush=True)
    sys.stdout.flush()


def bench_compile_cache():
    """Cold-process vs warm-process time-to-first-step for the LeNet
    train program (CPU backend, no TPU needed): process A compiles with
    ``FLAGS_compile_cache_dir`` set and serializes its executables;
    process B — a fresh interpreter, the elastic-restart/bench-respawn
    shape — hydrates them from disk.  ``baseline`` runs with the cache
    disabled (the pre-change behavior); cold-vs-baseline bounds the
    store overhead, cold/warm is the restart win the persistent cache
    exists for."""
    import os
    import subprocess
    import sys
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))

    def child(cache_dir, warm_start=False):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("FLAGS_compile_cache_dir", None)
        env.pop("PADDLE_TPU_BENCH_CC_WARMSTART", None)
        if cache_dir:
            env["FLAGS_compile_cache_dir"] = cache_dir
        if warm_start:
            env["PADDLE_TPU_BENCH_CC_WARMSTART"] = "1"
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--compile-cache-child"],
            env=env, cwd=here, capture_output=True, text=True, timeout=600)
        for line in out.stdout.splitlines():
            if line.startswith("CCCHILD="):
                return json.loads(line[len("CCCHILD="):])
        raise RuntimeError(
            f"compile-cache child failed rc={out.returncode}: "
            f"{out.stderr[-400:]}")

    with tempfile.TemporaryDirectory(prefix="ptcc_bench_") as d:
        baseline = child(None)
        cold = child(d)
        warm = child(d)
        warm_api = child(d, warm_start=True)

    assert warm["persistent_hits"] > 0, warm
    assert cold["persistent_misses"] > 0, cold
    speedup = cold["ttfs_s"] / max(warm["ttfs_s"], 1e-9)
    return {
        "baseline_ttfs_s": baseline["ttfs_s"],
        "cold_ttfs_s": cold["ttfs_s"],
        "warm_ttfs_s": warm["ttfs_s"],
        "warm_api_ttfs_s": warm_api["ttfs_s"],
        "warm_persistent_hits": warm["persistent_hits"],
        "cold_vs_warm_speedup": round(speedup, 2),
    }


def _checkpoint_child_main():
    """Child for bench_checkpoint: one train loop measured three ways —
    no checkpointing (baseline), ASYNC sharded snapshots every step
    (paddle_tpu/checkpoint/ — the no-pause path under test), and
    pause-the-world ``io.save_persistables`` every step (the legacy
    discipline).  The headline ``ckpt_overhead_frac`` is the async
    path's relative step-wall cost over baseline; the counters prove
    the step loop never blocked on serialization (zero faults, commits
    happened on the background thread, inflight pressure degrades to
    skipped snapshots — never to a stalled step)."""
    import os
    import sys
    import tempfile

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as fluid
    import paddle_tpu.checkpoint as pckpt
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.executor import Executor, Scope
    from paddle_tpu.core.program import Program, program_guard

    B, H = 2048, 512
    steps = int(os.environ.get("PADDLE_TPU_BENCH_CKPT_STEPS", "60"))
    # snapshot cadence: every N steps.  The overhead fraction is only
    # meaningful at a cadence where the ~state-size background write
    # fits inside its window — snapshotting 3 MB of state every 3 ms
    # step would measure CPU contention of a nonsense configuration,
    # not the async design.  10 steps of this model ≈ an order of
    # magnitude above the measured save wall.
    every = int(os.environ.get("PADDLE_TPU_BENCH_CKPT_EVERY", "10"))

    def build():
        prog, startup = Program(), Program()
        with program_guard(prog, startup), unique_name.guard():
            x = fluid.layers.data("x", [H])
            y = fluid.layers.data("y", [1])
            h = fluid.layers.fc(x, H, act="relu")
            pred = fluid.layers.fc(h, 1)
            diff = fluid.layers.elementwise_sub(pred, y)
            loss = fluid.layers.mean(fluid.layers.square(diff))
            fluid.optimizer.Adam(1e-3).minimize(loss)
        return prog, startup, loss

    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(B, H).astype("float32"),
            "y": rng.randn(B, 1).astype("float32")}

    class Mode:
        """One measured training context.  The three modes run
        INTERLEAVED in chunks of ``every`` steps — a sequential
        block-per-mode layout lets ambient load drift on a shared CI
        box land entirely on one mode and masquerade as (or mask) the
        checkpoint overhead; rotating chunks spreads it evenly."""

        def __init__(self, kind):
            self.kind = kind
            self.prog, startup, self.loss = build()
            self.scope, self.exe = Scope(), Executor()
            self.exe.run(startup, scope=self.scope)
            (lv,) = self.exe.run(self.prog, feed=feed,
                                 fetch_list=[self.loss], scope=self.scope)
            float(np.asarray(lv))                 # warmup compile
            self.snap = None
            self.dir = None
            if kind == "async":
                self.dir = tempfile.mkdtemp(prefix="ptckpt_bench_")
                self.snap = pckpt.scope_snapshotter(self.dir, self.prog,
                                                    self.scope, keep=4)
            elif kind == "pause":
                self.dir = tempfile.mkdtemp(prefix="ptckpt_pause_")
            self.walls = []
            self.n = 0

        def chunk(self):
            for _ in range(every):
                self.n += 1
                t0 = time.perf_counter()
                (lv,) = self.exe.run(self.prog, feed=feed,
                                     fetch_list=[self.loss],
                                     scope=self.scope)
                float(np.asarray(lv))             # per-step readback
                if self.n % every == 0:
                    if self.kind == "async":
                        self.snap.snapshot(self.n)
                    elif self.kind == "pause":
                        fluid.io.save_persistables(self.exe, self.dir,
                                                   self.prog)
                self.walls.append(time.perf_counter() - t0)

        def summary(self):
            # FULL mean, deliberately untrimmed: the pause-the-world
            # mode's cost lives entirely in its every-Nth-step spikes —
            # trimming outliers would trim away the measured thing
            mean_ms = sum(self.walls) / len(self.walls) * 1e3
            p99_ms = sorted(self.walls)[min(len(self.walls) - 1,
                                            int(len(self.walls) * 0.99))
                                        ] * 1e3
            stats = {}
            if self.snap is not None:
                self.snap.flush(timeout=60)
                st = self.snap.status()
                stats = {"snapshots": st["snapshots"],
                         "skipped_inflight": st["skipped_inflight"],
                         "faults": st["faults"],
                         "complete_steps": len(
                             pckpt.complete_steps(self.dir)),
                         "last_save_ms": st["save_ms"],
                         "collect_ms": st["collect_ms"],
                         "bytes": st["bytes"]}
                self.snap.close()
            return mean_ms, p99_ms, stats

    modes = [Mode("base"), Mode("async"), Mode("pause")]
    for _ in range(max(1, steps // every)):
        for m in modes:
            m.chunk()
    base_ms, base_p99, _ = modes[0].summary()
    async_ms, async_p99, async_stats = modes[1].summary()
    pause_ms, pause_p99, _ = modes[2].summary()
    out = {
        "steps": steps, "batch": B, "snapshot_every": every,
        "base_step_ms": round(base_ms, 3),
        "async_step_ms": round(async_ms, 3),
        "pause_step_ms": round(pause_ms, 3),
        "base_p99_ms": round(base_p99, 3),
        "async_p99_ms": round(async_p99, 3),
        "pause_p99_ms": round(pause_p99, 3),
        "ckpt_overhead_frac": round(max(0.0, async_ms - base_ms)
                                    / base_ms, 4),
        "pause_overhead_frac": round(max(0.0, pause_ms - base_ms)
                                     / base_ms, 4),
        "async": async_stats,
    }
    assert async_stats["faults"] == 0, out
    assert async_stats["complete_steps"] > 0, out
    print("CKPTBENCH=" + json.dumps(out), flush=True)
    sys.stdout.flush()


def bench_checkpoint():
    """Async-snapshot overhead vs pause-the-world checkpointing on the
    step loop (CPU-measured; no TPU needed).  Subprocess for a clean
    metrics registry.  Headline: ``ckpt_overhead_frac`` — the async
    sharded-snapshot path's step-wall overhead over the no-checkpoint
    baseline (acceptance: < 5%); ``pause_overhead_frac`` shows what the
    legacy synchronous save costs on the same loop."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--checkpoint-child"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=600)
    for line in out.stdout.splitlines():
        if line.startswith("CKPTBENCH="):
            return json.loads(line[len("CKPTBENCH="):])
    raise RuntimeError(
        f"checkpoint child failed rc={out.returncode}: "
        f"{out.stderr[-500:]}")


def _recovery_child_main():
    """Child for bench_recovery: MTTR of a pserver hard-kill, measured
    two ways over the SAME tiny sync-mode fleet + deterministic batch
    stream (tests/chaos_runner.py workers):

    - **supervised** — the ``distributed.supervisor`` owns the fleet;
      ps-0 is fault-armed to die mid-round; the supervisor detects the
      death, rolls the group back to the newest COMPLETE sharded
      checkpoint and resumes the trainer at the cut, zero human steps.
    - **manual** — the runner-choreographed baseline (the PR-11 chaos
      discipline): a script polls worker liveness at the 0.5 s cadence
      a shell runner realistically would, tears the fleet down, brings
      a fresh one up on new ports, waits for readiness, restarts the
      trainer at the cut.

    MTTR = the KILL moment (the dying pserver's flight dump stamps its
    ``fault_kill`` wall time — the same anchor in both modes) → first
    post-resume trainer step landing (the progress file's first
    write), with loss-curve parity against the no-fault local run
    asserted in BOTH modes — this measures kill-to-PARITY-resume, not
    kill-to-any-step.  The supervisor's wins are (a) sub-tick death
    detection vs the scripted poll cadence and (b) pipelined respawn:
    the trainer's process/import startup overlaps the replacement
    pservers' (``after_live=False``) instead of serializing behind a
    readiness wait."""
    import glob
    import os
    import subprocess
    import sys
    import tempfile

    import jax
    jax.config.update("jax_platforms", "cpu")

    repo = os.path.dirname(os.path.abspath(__file__))
    tests = os.path.join(repo, "tests")
    sys.path.insert(0, tests)
    runner = os.path.join(tests, "chaos_runner.py")
    pythonpath = os.pathsep.join(
        [repo, tests, os.environ.get("PYTHONPATH", "")])
    total = int(os.environ.get("PADDLE_TPU_BENCH_RECOVERY_STEPS", "10"))
    ckpt_every, kill_round = 2, 6

    from dist_model import build, free_ports, run_local
    local_losses, _ = run_local(total, build_fn=lambda: build(lr=0.05))

    def stitched_ok(progress_paths):
        got = {}
        for p in progress_paths:
            rec = json.load(open(p))
            start = rec["global_step"] - rec["step"]
            for j, l in enumerate(rec["losses"]):
                got[start + j + 1] = l
        if sorted(got) != list(range(1, total + 1)):
            return False
        return bool(np.allclose([got[i] for i in range(1, total + 1)],
                                local_losses, rtol=1e-4, atol=1e-5))

    def watch_first_write(path, deadline_s=300.0):
        """Poll tightly for the file's first complete write; returns
        its wall timestamp (mtime — finer than the poll cadence)."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            try:
                json.load(open(path))
                return os.stat(path).st_mtime
            except (OSError, ValueError):
                time.sleep(0.005)
        raise RuntimeError(f"no resume write at {path}")

    def kill_ts(flight_dir):
        """The fault_kill wall time from the dying pserver's flight
        dump — the shared MTTR anchor for both modes."""
        for path in sorted(glob.glob(os.path.join(flight_dir,
                                                  "flight_*.json"))):
            for ev in json.load(open(path)).get("events", ()):
                if ev.get("msg") == "fault_kill":
                    return ev["ts"]
        raise RuntimeError(f"no fault_kill note under {flight_dir}")

    # ---- supervised: the self-healing path ------------------------------
    from paddle_tpu.distributed.supervisor import (FleetSpec, RoleSpec,
                                                   Supervisor)
    sup_tmp = tempfile.mkdtemp(prefix="ptbench_rec_sup_")
    root = os.path.join(sup_tmp, "ck")
    common = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": pythonpath,
              "PADDLE_PSERVER_ENDPOINTS": "{ps_logicals}",
              "FLAGS_pserver_registry": "{registry}",
              "CHAOS_CKPT_DIR": "{checkpoint_root}",
              "CHAOS_CKPT_SHARDED": "1", "CHAOS_OPTIMIZER": "sgd"}
    spec = FleetSpec(
        registry="auto", checkpoint_root=root,
        rollback_roles=["ps", "trainer"], name="bench-recovery",
        roles={
            "ps": RoleSpec(
                count=2, logical="auto", health_role="PSERVER",
                argv=[sys.executable, runner],
                env={**common, "PADDLE_TRAINING_ROLE": "PSERVER",
                     "PADDLE_CURRENT_ENDPOINT": "{logical}",
                     "PADDLE_BIND_ENDPOINT": "127.0.0.1:0",
                     "CHAOS_CKPT_EVERY": str(ckpt_every)},
                env_once={0: {"FLAGS_fault_inject":
                              f"kill_after:apply_round:n={kill_round}",
                              "FLAGS_flight_record_dir": os.path.join(
                                  sup_tmp, "flight")}},
                backoff_s=0.05, action_deadline_s=180.0),
            # after_live=False: the rollback respawns the trainer
            # CONCURRENTLY with the replacement pservers (pipelined
            # recovery) — the registry-polling transport absorbs the
            # ordering, and resume_step is stable while the fleet is
            # down
            "trainer": RoleSpec(
                count=1, after=["ps"], after_live=False, done_ok=True,
                argv=[sys.executable, runner],
                env={**common, "PADDLE_TRAINING_ROLE": "TRAINER",
                     "DIST_TOTAL_STEPS": str(total),
                     "DIST_START_STEP": "{resume_step}",
                     "CHAOS_PROGRESS": os.path.join(
                         sup_tmp, "progress_{spawn}.json")},
                backoff_s=0.05, action_deadline_s=180.0)})
    sup = Supervisor(spec, poll_s=0.05, registry_poll_s=0.1).start()
    # the FIRST post-resume write must be caught LIVE (the trainer
    # rewrites the progress file every step, so a post-hoc mtime would
    # be the END of the run, not the resume) — a watcher thread polls
    # for incarnation 1's first complete write while the fleet runs
    import threading
    first_resume = {}

    def _watch_resume():
        try:
            first_resume["ts"] = watch_first_write(
                os.path.join(sup_tmp, "progress_1.json"))
        except RuntimeError:
            pass
    watcher = threading.Thread(target=_watch_resume, daemon=True)
    watcher.start()
    verdict = sup.wait(timeout=420)
    status = sup.status()
    sup.stop()
    assert verdict == "done", status
    watcher.join(timeout=10)
    assert stitched_ok(sorted(glob.glob(
        os.path.join(sup_tmp, "progress_*.json"))))
    supervised_mttr = first_resume["ts"] - kill_ts(os.path.join(sup_tmp,
                                                                "flight"))

    # ---- manual: the runner-choreographed baseline ----------------------
    man_tmp = tempfile.mkdtemp(prefix="ptbench_rec_man_")
    root_m = os.path.join(man_tmp, "ck")
    ready = os.path.join(man_tmp, "ready")
    poll_s = 0.5   # a scripted runner's realistic liveness cadence

    def spawn(role, env, **extra):
        return subprocess.Popen(
            [sys.executable, runner],
            env={**os.environ, **env, "PADDLE_TRAINING_ROLE": role,
                 **extra},
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def manual_phase(eps, start, extra_ps=None):
        env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": pythonpath,
               "PADDLE_PSERVER_ENDPOINTS": ",".join(eps),
               "PADDLE_READY_DIR": ready,
               "CHAOS_CKPT_DIR": root_m, "CHAOS_CKPT_SHARDED": "1",
               "CHAOS_CKPT_EVERY": str(ckpt_every),
               "CHAOS_OPTIMIZER": "sgd"}
        pss = [spawn("PSERVER", env, PADDLE_CURRENT_ENDPOINT=ep,
                     **(extra_ps or {}) if i == 0 else {})
               for i, ep in enumerate(eps)]
        from paddle_tpu.distributed import transport
        transport.wait_server_ready(eps, timeout=300, ready_dir=ready)
        progress = os.path.join(man_tmp, f"progress_{start}.json")
        tr = spawn("TRAINER", env, CHAOS_PROGRESS=progress,
                   DIST_TOTAL_STEPS=str(total),
                   DIST_START_STEP=str(start))
        return pss, tr, progress

    pss, tr, prog_a = manual_phase(
        [f"127.0.0.1:{p}" for p in free_ports(2)], 0,
        extra_ps={"FLAGS_fault_inject":
                  f"kill_after:apply_round:n={kill_round}",
                  "FLAGS_flight_record_dir": os.path.join(man_tmp,
                                                          "flight")})
    # the scripted runner's detect loop: poll at its cadence
    while pss[0].poll() is None:
        time.sleep(poll_s)
    # choreography: tear down survivors, restart from the cut
    for p in pss[1:] + [tr]:
        if p.poll() is None:
            p.kill()
        p.wait()
    import paddle_tpu.checkpoint as pckpt
    cut = pckpt.latest_complete_step(root_m) or 0
    pss_b, tr_b, prog_b = manual_phase(
        [f"127.0.0.1:{p}" for p in free_ports(2)], cut)
    resume_m = watch_first_write(prog_b)
    manual_mttr = resume_m - kill_ts(os.path.join(man_tmp, "flight"))
    assert tr_b.wait(timeout=300) == 0
    for p in pss_b:
        assert p.wait(timeout=120) == 0
    assert stitched_ok([prog_a, prog_b])

    out = {
        "steps": total, "ckpt_every_rounds": ckpt_every,
        "kill_round": kill_round,
        # both modes' MTTR floor is worker process startup; on a box
        # with fewer cores than concurrently-respawning workers the
        # supervisor's pipelined overlap buys little (imports contend)
        # — on a real one-worker-per-host fleet it collapses the
        # serial choreography chain.  host_cpus tells the reader which
        # regime this number was measured in (the bench_pipeline
        # precedent).
        "host_cpus": os.cpu_count(),
        "recovery_mttr_s": round(supervised_mttr, 3),
        "supervised_mttr_s": round(supervised_mttr, 3),
        "manual_mttr_s": round(manual_mttr, 3),
        "vs_manual": round(manual_mttr / max(supervised_mttr, 1e-9), 2),
        "supervised_spawns": {w["name"]: w["spawns"]
                              for w in status["workers"]},
        "parity": "rtol 1e-4 vs the no-fault local run, both modes",
    }
    print("RECOVERY=" + json.dumps(out), flush=True)
    sys.stdout.flush()


def bench_recovery():
    """MTTR of a hard-killed pserver: the self-healing supervisor
    (detect → rollback → checkpoint-hydrate → resume, zero human steps)
    vs the manual runner-choreographed restart baseline, on the same
    fleet and data stream, both required to resume at loss parity.
    Headline: ``recovery_mttr_s`` (lower is better — gated in
    tools/bench_compare.py LOWER_BETTER_KEYS).  CPU-measured: the
    control plane under test is transport/process-level, no TPU math
    in the measured window."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--recovery-child"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=900)
    for line in out.stdout.splitlines():
        if line.startswith("RECOVERY="):
            return json.loads(line[len("RECOVERY="):])
    raise RuntimeError(
        f"recovery child failed rc={out.returncode}: "
        f"{out.stderr[-800:]}")


def _pipeline_child_main():
    """Child for bench_pipeline: K-stage mnist pipeline on a K-device
    virtual CPU mesh (one stage per device, worker threads overlap).
    GPipe vs 1F1B at M in {4, 8, 16} microbatches vs the naive
    sequential stage-by-stage baseline; reports samples/s, measured +
    slot-grid bubble fraction, and per-stage utilization."""
    import os
    import sys

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.pipeline as pipe
    from paddle_tpu.models import mnist

    K = int(os.environ.get("PADDLE_TPU_BENCH_PIPE_STAGES", "4"))
    mb = int(os.environ.get("PADDLE_TPU_BENCH_PIPE_MICROBATCH", "32"))
    reps = int(os.environ.get("PADDLE_TPU_BENCH_PIPE_REPS", "3"))
    devices = jax.devices()[:K]
    rng = np.random.RandomState(0)
    # host_cpus bounds the thread-overlap win on the CPU mesh: the
    # sequential baseline already uses every core via XLA intra-op
    # threading, so speedup > 1 here measures pure schedule overlap;
    # on a >=K-core (or multi-chip) host the full GPipe ratio applies
    out = {"stages": K, "microbatch_rows": mb, "host_cpus": os.cpu_count(),
           "device": devices[0].platform, "configs": {}}

    def timed(tr, feed, mode, n):
        t0 = time.perf_counter()
        res = None
        for _ in range(n):
            res = tr.run(feed, mode=mode)
        return time.perf_counter() - t0, res

    for M in (4, 8, 16):
        B = mb * M
        feed = {"pixel": rng.randn(B, 1, 28, 28).astype("float32"),
                "label": rng.randint(0, 10, (B, 1)).astype("int64")}
        cfg = {"batch": B, "microbatches": M,
               "bubble_bound": round(pipe.gpipe_bubble_bound(K, M), 4)}

        prog, startup, (feeds, loss, acc) = _fresh(lambda: mnist.build())
        pp = pipe.PipelineTranspiler().transpile(
            prog, startup, num_stages=K, num_microbatches=M,
            loss_name=loss.name)
        tr = pipe.PipelineTrainer(pp, schedule="gpipe",
                                  devices=devices).init()
        tr.run(feed, mode="sequential")  # warmup: compiles every stage
        dt, _ = timed(tr, feed, "sequential", reps)
        cfg["sequential_samples_per_sec"] = round(B * reps / dt, 1)

        for sched in ("gpipe", "1f1b"):
            trs = pipe.PipelineTrainer(pp, schedule=sched,
                                       devices=devices).init()
            trs.run(feed)  # warmup (slots mode)
            dt, res = timed(trs, feed, None, reps)
            cfg[sched] = {
                "samples_per_sec": round(B * reps / dt, 1),
                "speedup_vs_sequential": round(
                    (B * reps / dt) / cfg["sequential_samples_per_sec"],
                    3),
                "bubble_fraction": round(res.bubble_fraction, 4),
                "bubble_fraction_slots": round(
                    res.bubble_fraction_slots, 4),
                "stage_utilization": [round(u, 3)
                                      for u in res.stage_utilization],
                "stage_activation_bytes": res.stage_activation_bytes,
            }
        out["configs"][f"m{M}"] = cfg

    m8 = out["configs"]["m8"]
    best = max(("gpipe", "1f1b"), key=lambda s: m8[s]["samples_per_sec"])
    out["pipeline_samples_per_sec"] = m8[best]["samples_per_sec"]
    out["best_schedule_m8"] = best
    out["pipeline_vs_sequential_speedup"] = \
        m8[best]["speedup_vs_sequential"]
    out["bubble_fraction_m8"] = m8[best]["bubble_fraction"]
    out["bubble_bound_m8"] = m8["bubble_bound"]
    print("PIPELINE=" + json.dumps(out), flush=True)
    sys.stdout.flush()


def bench_pipeline():
    """Pipeline parallelism machinery: K-stage mnist training, GPipe vs
    1F1B vs naive sequential stage execution at M in {4, 8, 16}
    microbatches.  Subprocess on a virtual K-device CPU mesh (the axon
    plugin pins this process to 1 device; stage overlap needs one
    device per stage) — on a real multi-chip host the same harness
    measures hardware overlap, here it measures the scheduling plane.
    Headline: best-schedule samples/s at M=8, with the measured bubble
    fraction vs the (K-1)/(M+K-1) GPipe model."""
    import os
    import subprocess
    import sys

    K = int(os.environ.get("PADDLE_TPU_BENCH_PIPE_STAGES", "4"))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={K}").strip()
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--pipeline-child"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=900)
    for line in out.stdout.splitlines():
        if line.startswith("PIPELINE="):
            return json.loads(line[len("PIPELINE="):])
    raise RuntimeError(
        f"pipeline child failed rc={out.returncode}: {out.stderr[-500:]}")


def bench_scaling():
    """Weak-scaling efficiency on the virtual 8-device CPU mesh (see
    paddle_tpu/parallel/scaling.py — per-device compiled cost, the only
    honest scaling instrument on a 1-core host).  Subprocess because the
    axon TPU plugin, once registered, pins this process to 1 device."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import json; from paddle_tpu.parallel.scaling import "
            "scaling_report; print('SCALING=' + "
            "json.dumps(scaling_report(per_device_batch=4, big_dp=8)))")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         cwd=os.path.dirname(os.path.abspath(__file__)),
                         capture_output=True, text=True, timeout=900)
    for line in out.stdout.splitlines():
        if line.startswith("SCALING="):
            rep = json.loads(line[len("SCALING="):])
            assert rep["eff_flops"] >= 0.85, rep
            # analysis-only tagging happens centrally in main() via
            # ANALYSIS_CONFIGS (one policy point, covers error records)
            return rep
    raise RuntimeError(f"scaling child failed: {out.stderr[-500:]}")


# Ordered so the headline + the claims under review land first if the
# budget runs out.  (name, fn, per-config deadline seconds, needs_tpu)
CONFIG_TABLE = [
    ("resnet50", bench_resnet50, 480, True),
    ("deepfm", bench_deepfm, 420, True),
    # needs_tpu=False: off-TPU it self-degrades to an ``analysis: true``
    # structural artifact (the one backend-conditional exception to the
    # static ANALYSIS_CONFIGS tagging); on-chip it is a measured config
    # on the ROADMAP item 5 capture list (DeepFM >= 400k samples/s)
    ("deepfm_fused", bench_deepfm_fused, 420, False),
    ("mnist", bench_mnist, 300, True),
    ("flash_attention_seq8k", bench_flash_attention_long, 600, True),
    ("ring_shard_s4096", bench_ring_shard, 420, True),
    ("transformer_seq256", bench_transformer, 420, True),
    ("stacked_lstm", bench_stacked_lstm, 300, True),
    ("resnet50_datapath", bench_resnet50_datapath, 420, True),
    ("rpc_transport", bench_rpc_transport, 300, False),
    ("serving", bench_serving, 420, False),
    # needs_tpu=False: CPU-measured policy evidence, self-labels
    # ``analysis: true`` off-TPU (the deepfm_fused precedent); the
    # on-chip number is the ROADMAP item 1 'decode' capture row
    ("decode", bench_decode, 420, False),
    # refcounted block lifecycle: shared-prefix dedup + overcommit
    # preemption legs (CPU policy evidence off-TPU, like decode)
    ("decode_prefix", bench_decode_prefix, 420, False),
    # quantized KV cache residency: int8 blocks + scale pools vs fp32
    # at the same pool bytes (CPU policy evidence off-TPU, like decode)
    ("decode_kv_int8", bench_decode_kv_int8, 420, False),
    ("pipeline", bench_pipeline, 900, False),
    ("compile_cache", bench_compile_cache, 600, False),
    ("checkpoint", bench_checkpoint, 600, False),
    # CPU-measured control-plane wall time (like rpc_transport): the
    # supervisor's kill-to-parity-resume MTTR vs the manual baseline
    ("recovery", bench_recovery, 900, False),
    ("scaling_dp8", bench_scaling, 900, False),
]


def _config_table():
    """The real table, or a test-injected one (file exporting
    CONFIG_TABLE) so tests/test_bench_driver.py can exercise the
    orchestrator's timeout/restart/budget paths without a TPU."""
    import importlib.util
    import os

    path = os.environ.get("PADDLE_TPU_BENCH_TEST_TABLE")
    if not path:
        return CONFIG_TABLE
    spec = importlib.util.spec_from_file_location("bench_test_table", path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m.CONFIG_TABLE


def _probe_main():
    """Child: one tiny put + readback against the default backend, so a
    sick tunnel is diagnosable (and kill-able) from outside.

    Emits a TCP pre-check of the tunnel endpoint first: the axon plugin
    retries forever on a dead endpoint instead of failing fast, so
    distinguishing 'port refused' (endpoint down) from 'connected but
    hung' (protocol-level sickness) in the artifact tells the reader
    which infrastructure layer died."""
    import os
    import socket

    tcp = "skipped"
    # the environment pins JAX_PLATFORMS=axon globally, so "is the env
    # var set" is NOT the TPU-vs-CPU signal — only a cpu pin skips the
    # tunnel check
    if os.environ.get("JAX_PLATFORMS", "axon") != "cpu":
        try:
            port = int(os.environ.get("PADDLE_TPU_TUNNEL_PORT", "8103"))
        except ValueError:
            port = 8103  # malformed override must not kill diagnosis
        try:
            socket.create_connection(("127.0.0.1", port), 3).close()
            tcp = "connected"
        except ConnectionRefusedError:
            tcp = "refused"
        except OSError as e:
            tcp = f"error: {e}"
        print("PROBETCP=" + tcp, flush=True)

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # the env var alone is not honored once the axon plugin
        # registers; pin the config like tests/conftest.py does
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    t0 = time.perf_counter()
    d = jax.device_put(np.ones((8, 128), np.float32))
    float(np.asarray(d)[0, 0])
    init_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    d = jax.device_put(np.ones((8, 128), np.float32))
    float(np.asarray(d)[0, 0])
    rtt_s = time.perf_counter() - t0
    print("PROBE=" + json.dumps({
        "ok": True, "backend_init_s": round(init_s, 2),
        "rtt_ms": round(rtt_s * 1e3, 1), "tunnel_tcp": tcp,
        "platform": jax.devices()[0].platform}), flush=True)


def _fleet_aggregator():
    """Multi-host runs: PADDLE_TPU_BENCH_FLEET_ENDPOINTS names the other
    workers' RPC ports (``trainer-0=host:port,trainer-1=host:port`` —
    bare ``host:port`` entries get positional names) and the per-config
    telemetry export then carries a cross-worker ``fleet`` merge with
    per-worker labels (observability/aggregate.py).  Unset (the
    single-host default) adds nothing."""
    spec = os.environ.get("PADDLE_TPU_BENCH_FLEET_ENDPOINTS", "")
    if not spec:
        return None
    workers = {}
    for i, item in enumerate(x.strip() for x in spec.split(",")):
        if not item:
            continue
        name, _, ep = item.rpartition("=")
        workers[name or f"worker-{i}"] = ep
    from paddle_tpu.observability.aggregate import FleetAggregator
    return FleetAggregator(workers)


def _worker_main(names):
    """Child: run the named configs in order, one flushed line each.

    Per config, the runtime telemetry layer is reset before and exported
    after (``BENCHSTATS=`` line), so each config's compile-cache
    hits/misses, lowering/compile time and transfer bytes land in the
    orchestrator's ``step_stats.json`` artifact — a BENCH_r*.json
    regression then comes with the telemetry that explains it."""
    try:
        from paddle_tpu import observability as _obs
    except Exception:  # telemetry must never take the bench down
        _obs = None
    try:
        fleet = _fleet_aggregator() if _obs is not None else None
    except Exception:
        fleet = None
    fns = dict((n, f) for n, f, _, _ in _config_table())
    for name in names:
        print("BENCHSTART=" + name, flush=True)
        if _obs is not None:
            _obs.reset()
        _take_roofline()  # a previous config's attribution must not leak
        try:
            result = fns[name]()
        except Exception as e:  # broken config must not hide the rest
            result = {"error": repr(e)[:200]}
        rf = _take_roofline()
        if rf and isinstance(result, dict) and "error" not in result:
            result.setdefault("roofline", rf)
        print("BENCHRESULT=" + json.dumps({"name": name, "result": result}),
              flush=True)
        if _obs is not None:
            try:
                tele = _obs.export(step_tail=8)
                if fleet is not None:
                    tele["fleet"] = fleet.export()
                print("BENCHSTATS=" + json.dumps(
                    {"name": name, "telemetry": tele}),
                    flush=True)
            except Exception:
                pass


def _run_streaming(cmd, handle_line, deadline_for, kill_grace=5.0):
    """Run cmd, dispatching stdout lines to handle_line.  deadline_for()
    returns the absolute monotonic deadline for the current wait (it can
    move as configs complete).  Returns (rc, timed_out)."""
    import queue
    import subprocess
    import threading

    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    q = queue.Queue()

    def pump():
        for line in p.stdout:
            q.put(line)
        q.put(None)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    timed_out = False
    while True:
        timeout = deadline_for() - time.monotonic()
        if timeout <= 0:
            timed_out = True
            break
        try:
            line = q.get(timeout=min(timeout, 5.0))
        except queue.Empty:
            continue
        if line is None:
            break
        handle_line(line.rstrip("\n"))
    if timed_out:
        # drain lines that raced the deadline (a result printed just
        # before expiry must not be recorded as a timeout)
        while True:
            try:
                line = q.get_nowait()
            except queue.Empty:
                break
            if line is None:
                break
            handle_line(line.rstrip("\n"))
        p.kill()
    p.wait(timeout=kill_grace if timed_out else None)
    return p.returncode, timed_out


_PROBE_COUNT = 0


def _probe(budget_deadline):
    import os
    import sys
    global _PROBE_COUNT

    # PADDLE_TPU_BENCH_PROBE_TIMEOUT_S may be a comma list consumed one
    # entry per probe (last entry repeats) — the driver tests script a
    # fail-then-recover tunnel with "0,240"
    spec = os.environ.get("PADDLE_TPU_BENCH_PROBE_TIMEOUT_S", "240")
    parts = [p.strip() for p in str(spec).split(",") if p.strip()]
    probe_timeout = float(parts[min(_PROBE_COUNT, len(parts) - 1)]
                          if parts else 240.0)
    _PROBE_COUNT += 1
    deadline = min(time.monotonic() + probe_timeout, budget_deadline)
    result = {}
    tcp = {}

    def on_line(line):
        if line.startswith("PROBE="):
            result.update(json.loads(line[len("PROBE="):]))
        elif line.startswith("PROBETCP="):
            tcp["tunnel_tcp"] = line[len("PROBETCP="):]

    rc, timed_out = _run_streaming(
        [sys.executable, __file__, "--probe"], on_line, lambda: deadline)
    if not result:
        result = {"ok": False,
                  "error": "timeout" if timed_out else f"rc={rc}", **tcp}
    return result


# analysis-only entries: cost-model/compiled-cost numbers, not on-chip
# wall time — tagged in the artifact so an all-skip TPU round whose only
# survivors are analysis entries cannot read as a measured round
ANALYSIS_CONFIGS = frozenset({"scaling_dp8"})


def main():
    import os
    import sys

    t_start = time.monotonic()
    budget = float(os.environ.get("PADDLE_TPU_BENCH_BUDGET_S", "1200"))
    budget_deadline = t_start + budget

    def emit_partial(name, result):
        # partials go to STDERR: stdout stays exactly ONE JSON line (the
        # driver contract), while a timeout-killed run still leaves the
        # finished configs readable in the captured stderr tail
        print(json.dumps({"partial": True, "config": name,
                          "result": result}), file=sys.stderr, flush=True)

    probe = _probe(budget_deadline)
    emit_partial("_tunnel_probe", probe)

    configs = {}
    telemetry = {}
    reprobes = []
    pending = [(n, dl, tpu) for n, _, dl, tpu in _config_table()]
    if not probe.get("ok"):
        # dead tunnel: don't burn the budget on TPU configs YET — the
        # CPU-mesh entries still run, and the re-probe loop below keeps
        # trying the tunnel with backoff for as long as budget remains
        # (BENCH_r05 threw away 929 s of budget after ONE refused
        # connect at t=0; never again)
        for name, _, tpu in pending:
            if tpu:
                configs[name] = {"skipped": "tunnel probe failed"}
                emit_partial(name, configs[name])
        pending = [p for p in pending if not p[2]]

    _drain_configs(pending, configs, telemetry, budget_deadline,
                   emit_partial)

    # -- tunnel re-probe with exponential backoff -------------------------
    # configs skipped because the tunnel was down at their turn get
    # retried as soon as a later probe succeeds; backoff doubles from
    # PADDLE_TPU_BENCH_REPROBE_BACKOFF_S (default 20 s, capped 300 s)
    def _tunnel_skipped():
        return [(n, dl, tpu) for n, _, dl, tpu in _config_table()
                if tpu and isinstance(configs.get(n), dict)
                and str(configs[n].get("skipped", "")).startswith(
                    ("tunnel probe failed", "2 consecutive"))]

    backoff = float(os.environ.get(
        "PADDLE_TPU_BENCH_REPROBE_BACKOFF_S", "20"))
    while backoff > 0 and _tunnel_skipped() and \
            budget_deadline - time.monotonic() > backoff + 90:
        time.sleep(backoff)
        probe2 = _probe(budget_deadline)
        reprobes.append(probe2)
        emit_partial("_tunnel_reprobe", probe2)
        if not probe2.get("ok"):
            backoff = min(backoff * 2, 300.0)
            continue
        probe = probe2            # the artifact reports the LIVE probe
        retry = _tunnel_skipped()
        for name, _, _ in retry:
            configs.pop(name, None)
        _drain_configs(retry, configs, telemetry, budget_deadline,
                       emit_partial)

    for name in ANALYSIS_CONFIGS:
        if isinstance(configs.get(name), dict):
            configs[name].setdefault("analysis", True)

    _emit_summary(configs, telemetry, probe, reprobes, t_start)


def _drain_configs(pending, configs, telemetry, budget_deadline,
                   emit_partial):
    """Run the named configs through restartable worker subprocesses
    (mutates ``configs``/``telemetry``; see main for the contract)."""
    import os
    import sys

    timeouts_in_a_row = 0
    while pending:
        remaining_budget = budget_deadline - time.monotonic()
        if remaining_budget < 60:
            for name, _, _ in pending:
                configs[name] = {"skipped": "budget"}
                emit_partial(name, configs[name])
            break
        if timeouts_in_a_row >= 2:
            # tunnel went sick mid-run: stop burning budget on TPU
            # configs, keep anything CPU-only
            for name, _, tpu in list(pending):
                if tpu:
                    configs[name] = {"skipped":
                                     "2 consecutive config timeouts"}
                    emit_partial(name, configs[name])
            pending = [p for p in pending if not p[2]]
            timeouts_in_a_row = 0
            continue

        names = [n for n, _, _ in pending]
        caps = dict((n, dl) for n, dl, _ in pending)
        state = {"current": None, "started": time.monotonic(),
                 "n_results": 0}

        def on_line(line):
            if line.startswith("BENCHSTART="):
                state["current"] = line[len("BENCHSTART="):]
                state["started"] = time.monotonic()
            elif line.startswith("BENCHRESULT="):
                rec = json.loads(line[len("BENCHRESULT="):])
                configs[rec["name"]] = rec["result"]
                emit_partial(rec["name"], rec["result"])
                state["current"] = None
                # restart the between-configs clock: deadline_for must
                # not judge the NEXT config by the finished one's start
                state["started"] = time.monotonic()
                state["n_results"] += 1
            elif line.startswith("BENCHSTATS="):
                # a worker killed at its deadline can truncate this
                # (multi-KB) line mid-print; telemetry must never take
                # the bench down
                try:
                    rec = json.loads(line[len("BENCHSTATS="):])
                    telemetry[rec["name"]] = rec["telemetry"]
                except (ValueError, KeyError):
                    pass

        def deadline_for():
            cap = caps.get(state["current"], 300) if state["current"] \
                else 120  # startup/import window
            return min(state["started"] + cap, budget_deadline)

        n_done_before = len(configs)
        rc, timed_out = _run_streaming(
            [sys.executable, __file__, "--worker", ",".join(names)],
            on_line, deadline_for)
        if state["n_results"]:
            timeouts_in_a_row = 0  # "consecutive" means no success between
        if timed_out and state["current"]:
            configs[state["current"]] = {"error": "timeout", "after_s":
                                         round(time.monotonic()
                                               - state["started"], 1)}
            emit_partial(state["current"], configs[state["current"]])
            timeouts_in_a_row += 1
        elif timed_out:
            timeouts_in_a_row += 1
        pending = [p for p in pending if p[0] not in configs]
        if not timed_out and rc == 0:
            break  # worker finished the whole list
        if not timed_out and rc != 0 and state["current"]:
            # worker crashed mid-config (not via the per-config except:
            # e.g. a segfault); record it and continue with the rest
            configs[state["current"]] = {"error": f"worker rc={rc}"}
            emit_partial(state["current"], configs[state["current"]])
            pending = [p for p in pending if p[0] not in configs]
        elif not timed_out and rc != 0 and len(configs) == n_done_before:
            # crashed before reaching any config and made no progress —
            # don't crash-loop until the budget runs out
            for name, _, _ in pending:
                configs[name] = {"error": f"worker rc={rc} at startup"}
                emit_partial(name, configs[name])
            break


def _auto_compare(configs):
    """Regression gate on the freshly completed round: compare against
    the last round that actually measured something (BENCH_r04 timed
    out, r05 was all-skip — those are passed over) and record the
    verdict in the summary JSON (tools/bench_compare.py is also the
    standalone CI gate).  PADDLE_TPU_BENCH_COMPARE_PREV names a
    specific baseline; set it empty to disable.  Comparison failures
    are recorded, never fatal — the measured numbers always land."""
    import os
    import sys

    prev = os.environ.get("PADDLE_TPU_BENCH_COMPARE_PREV")
    if prev == "":
        return None
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "tools"))
    try:
        import bench_compare
        base_path = prev or bench_compare.find_baseline(here)
        if not base_path:
            return {"skipped": "no measured baseline round found"}
        old = bench_compare.load_round(base_path)
        cmp = bench_compare.compare(old, {"configs": configs})
        cmp["baseline"] = os.path.basename(base_path)
        return cmp
    except Exception as e:
        return {"error": repr(e)[:200]}
    finally:
        sys.path.pop(0)


def _emit_summary(configs, telemetry, probe, reprobes, t_start):
    import os

    # per-config telemetry artifact (cache hits, compile time, transfer
    # bytes — the numbers that EXPLAIN a BENCH trajectory regression);
    # PADDLE_TPU_BENCH_STATS_PATH overrides, empty disables
    stats_path = os.environ.get("PADDLE_TPU_BENCH_STATS_PATH",
                                "step_stats.json")
    if stats_path:
        try:
            with open(stats_path, "w") as f:
                json.dump({"configs": telemetry}, f, indent=2,
                          sort_keys=True)
        except OSError:
            stats_path = None

    primary = configs.get("resnet50", {}).get("images_per_sec", 0.0)
    tfm = configs.get("transformer_seq256", {})
    if tfm.get("tokens_per_sec"):
        configs["transformer_seq256"]["vs_a100"] = round(
            tfm["tokens_per_sec"] / A100_TRANSFORMER_TOK_S, 3)
    # an all-skip/analysis-only round must be legible as one: count the
    # configs that produced a MEASURED number this round
    measured = sum(
        1 for v in configs.values()
        if isinstance(v, dict) and not v.get("skipped")
        and not v.get("error") and not v.get("analysis"))
    comparison = _auto_compare(configs)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": primary,
        "unit": "images/sec",
        "vs_baseline": round(primary / A100_RESNET50_IMG_S, 3),
        "tunnel_probe": probe,
        "reprobes": len(reprobes),
        "measured_configs": measured,
        "elapsed_s": round(time.monotonic() - t_start, 1),
        "step_stats_path": stats_path or None,
        "comparison": comparison,
        "configs": configs,
    }), flush=True)


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "--probe":
        _probe_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker_main(sys.argv[2].split(","))
    elif len(sys.argv) > 1 and sys.argv[1] == "--compile-cache-child":
        _compile_cache_child_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--checkpoint-child":
        _checkpoint_child_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--recovery-child":
        _recovery_child_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--pipeline-child":
        _pipeline_child_main()
    else:
        main()
